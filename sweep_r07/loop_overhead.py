"""Isolate the FRAMEWORK's per-step loop overhead from this box's
environment ceilings (1 CPU core, 0.04 GB/s tunnel — loop_e2e.py).

At CIFAR shapes (1.6 MB/batch) transfer and assembly are negligible, so
full-loop steps/sec vs the bare compiled step on the SAME executable
exposes the fixed per-step cost of the loop machinery itself (batch
iterator -> prefetch handoff -> step dispatch -> metrics accum ->
checkpoint-cadence check). That fixed cost transfers to the north-star
config on a real host (where per-core assembly x ~100 cores and local
DMA keep up): loop/step efficiency ~= step_ms / (step_ms + overhead_ms).
"""

import json
import shutil
import time

import jax
import numpy as np

from zookeeper_tpu.core import configure
from zookeeper_tpu.training import TrainingExperiment


def main():
    shutil.rmtree("/tmp/loop_oh_ckpt", ignore_errors=True)
    exp = TrainingExperiment()
    configure(
        exp,
        {
            "loader.dataset": "SyntheticCifar10",
            "loader.dataset.num_train_examples": 8192,
            "loader.preprocessing": "ImageClassificationPreprocessing",
            "loader.preprocessing.height": 32,
            "loader.preprocessing.width": 32,
            "loader.preprocessing.channels": 3,
            "loader.prefetch": 2,
            "model": "BinaryNet",  # CIFAR-native zoo family
            "model.compute_dtype": "bfloat16",
            "optimizer": "Adam",
            "partitioner": "DataParallelPartitioner",
            "batch_size": 128,
            "epochs": 5,
            "validate": False,
            "verbose": False,
            "checkpointer.directory": "/tmp/loop_oh_ckpt",
            "checkpointer.save_every_steps": 100,
            "checkpointer.save_every_epochs": 0,
        },
        name="experiment",
    )
    history = exp.run()
    eps = [e["examples_per_sec"] for e in history["train"]]
    steady = float(np.mean(eps[1:]))
    loop_step_ms = 128.0 / steady * 1e3

    # Bare step on the SAME state/loader shapes: rebuild the compiled
    # step exactly as Experiment.run does and time a chain.
    from zookeeper_tpu.training import make_train_step

    state = exp.final_state
    partitioner = exp.partitioner
    jit_step = partitioner.compile_step(make_train_step(), state)
    sharding = partitioner.batch_sharding()
    batch = next(
        iter(exp.loader.batches("train", epoch=0, sharding=sharding))
    )
    state, metrics = jit_step(state, batch)  # warm
    float(jax.device_get(metrics["loss"]))

    def run_chain(n):
        nonlocal state
        t0 = time.perf_counter()
        for _ in range(n):
            state, m = jit_step(state, batch)
        float(jax.device_get(m["loss"]))
        return time.perf_counter() - t0

    t1 = min(run_chain(20) for _ in range(4))
    t2 = min(run_chain(100) for _ in range(4))
    bare_step_ms = (t2 - t1) / 80 * 1e3

    # NOTE (measured 2026-07-31): the loop-vs-bare delta on THIS box is
    # ~104 ms/step and is TUNNEL cost, not framework Python — the bare
    # chain dispatches steps back-to-back against one resident batch
    # (transfers and RPCs amortize), while the loop must device_put
    # each fresh batch through the 40 MB/s link (1.6 MB -> ~40 ms) and
    # pay per-dispatch RPC latency. The loop's own Python (iterate,
    # accum append, cadence checks) is microseconds; no real-hardware
    # projection is derivable from this box's delta, so none is
    # printed.
    out = {
        "loop_examples_per_sec_by_epoch": [round(e, 1) for e in eps],
        "loop_step_ms": round(loop_step_ms, 2),
        "bare_step_ms": round(bare_step_ms, 2),
        "overhead_ms_per_step_tunnel_inclusive": round(
            loop_step_ms - bare_step_ms, 2
        ),
    }
    print(json.dumps(out))
    exp.checkpointer.close()


if __name__ == "__main__":
    main()
