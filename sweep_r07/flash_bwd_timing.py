"""On-chip timing: flash fwd+bwd vs XLA's fused dense path, long-chain
marginal protocol (BASELINE.md methodology). The forward measured at
parity with dense (round-6); this asks the same honest question of the
recompute backward."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from zookeeper_tpu.ops import attention_reference, flash_attention


def time_marginal(run, n1, n2, rounds=4):
    t1 = min(run(n1) for _ in range(rounds))
    t2 = min(run(n2) for _ in range(rounds))
    return (t2 - t1) / (n2 - n1)


def bench(s, causal=True, b=1, h=8, d=64, dtype=jnp.bfloat16):
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.normal(size=(b, s, h, d)).astype(np.float32), dtype
    )
    q, k, v = mk(), mk(), mk()

    def make_chain(fn):
        @jax.jit
        def val_grad(q):
            return jax.value_and_grad(
                lambda q: fn(q).astype(jnp.float32).sum()
            )(q)

        def run(n):
            x = q
            t0 = time.perf_counter()
            for _ in range(n):
                _, g = val_grad(x)
                # Data dependency: next iterate consumes the gradient.
                x = x + 0 * g.astype(x.dtype)
            float(jax.device_get(g.astype(jnp.float32).sum()))
            return time.perf_counter() - t0

        run(2)  # warm compile
        return run

    flash_run = make_chain(
        lambda q: flash_attention(q, k, v, causal=causal, interpret=False)
    )
    dense_run = make_chain(
        lambda q: attention_reference(q, k, v, causal=causal)
    )
    mf = time_marginal(flash_run, 10, 40) * 1e3
    md = time_marginal(dense_run, 10, 40) * 1e3
    print(
        f"s={s} causal={causal} {np.dtype(dtype).name}: "
        f"flash fwd+bwd {mf:.2f} ms/step, dense fwd+bwd {md:.2f} ms/step "
        f"(ratio {mf / md:.2f}x)"
    )


if __name__ == "__main__":
    for s in (2048, 4096, 8192):
        bench(s)
