"""VERDICT r4 #2: measure the FULL ``Experiment.run()`` loop on the real
chip at the north-star shape (QuickNet-Large, b128, int8, ImageNet
shapes) and decompose loop-vs-bare-step efficiency.

Every prior on-chip number times the bare compiled step with an
HBM-resident batch; this probe drives the real host pipeline ->
prefetch -> jitted step -> metrics -> checkpoint cadence and names the
gap per stage. Stages measured independently:

  1. host assembly, native fused path (augment off -> C++ gather+affine)
  2. host assembly, augmented path (RandomResizedCrop, per-example numpy)
  3. host->device transfer of an assembled batch (the remote-TPU tunnel)
  4. the full Experiment.run() loop (epoch examples_per_sec, excluding
     the compile epoch)

Context this box cannot hide: ONE CPU core (a real v5e host has ~100+)
and the TPU sits behind a network tunnel (~100 ms sync latency, limited
bandwidth vs local PCIe/DMA). Stages 1-3 quantify exactly how much of
any loop shortfall is environment, not framework.
"""

import json
import time

import numpy as np


def measure_host_assembly(augment: bool, n_batches: int = 8):
    from zookeeper_tpu.core import configure
    from zookeeper_tpu.data import (
        DataLoader,
        batch_iterator,
    )

    loader = DataLoader()
    configure(
        loader,
        {
            "dataset": "SyntheticImageNet",
            "dataset.num_train_examples": 1024,
            "preprocessing": "ImageClassificationPreprocessing",
            "preprocessing.height": 224,
            "preprocessing.width": 224,
            "preprocessing.channels": 3,
            "preprocessing.augment": augment,
            "preprocessing.random_resized_crop": augment,
            "batch_size": 128,
            "prefetch": 0,
        },
        name="loader",
    )
    it = loader.batches("train", epoch=0)
    next(it)  # First batch warms source construction + any native build.
    t0 = time.perf_counter()
    seen = 0
    for b in it:
        seen += b["input"].shape[0]
        if seen >= n_batches * 128:
            break
    dt = time.perf_counter() - t0
    return seen / dt


def measure_transfer(n_batches: int = 12):
    """device_put + readback barrier for an assembled float32 batch:
    the tunnel's sustained host->device bandwidth at batch granularity."""
    import jax
    import jax.numpy as jnp

    batch = np.random.default_rng(0).random(
        (128, 224, 224, 3), np.float32
    )
    nbytes = batch.nbytes
    x = jax.device_put(batch)  # warm
    float(jnp.sum(x[0, 0, 0]))
    t0 = time.perf_counter()
    for _ in range(n_batches):
        x = jax.device_put(batch)
    float(jnp.sum(x[0, 0, 0]))  # completion barrier
    dt = time.perf_counter() - t0
    return n_batches * nbytes / dt, n_batches * 128 / dt


def measure_full_loop(epochs: int = 6, augment: bool = False):
    """The real TrainingExperiment at north-star config; returns the
    per-epoch examples_per_sec records (epoch 0 includes compile)."""
    import shutil

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.training import TrainingExperiment

    shutil.rmtree("/tmp/loop_e2e_ckpt", ignore_errors=True)
    exp = TrainingExperiment()
    configure(
        exp,
        {
            "loader.dataset": "SyntheticImageNet",
            "loader.dataset.num_train_examples": 2048,
            "loader.preprocessing": "ImageClassificationPreprocessing",
            "loader.preprocessing.height": 224,
            "loader.preprocessing.width": 224,
            "loader.preprocessing.channels": 3,
            "loader.preprocessing.augment": augment,
            "loader.preprocessing.random_resized_crop": augment,
            "loader.prefetch": 2,
            "model": "QuickNetLarge",
            "model.compute_dtype": "bfloat16",
            "model.binary_compute": "int8",
            "optimizer": "Adam",
            "partitioner": "DataParallelPartitioner",
            "batch_size": 128,
            "epochs": epochs,
            "validate": False,
            "verbose": True,
            "checkpointer.directory": "/tmp/loop_e2e_ckpt",
            "checkpointer.save_every_steps": 100,
            "checkpointer.save_every_epochs": 0,
        },
        name="experiment",
    )
    history = exp.run()
    exp.checkpointer.close()
    return [e["examples_per_sec"] for e in history["train"]]


def main():
    out = {}
    out["host_assembly_native_img_s"] = round(
        measure_host_assembly(augment=False), 1
    )
    out["host_assembly_augmented_img_s"] = round(
        measure_host_assembly(augment=True, n_batches=2), 1
    )
    gbps, img_s = measure_transfer()
    out["transfer_gb_s"] = round(gbps / 1e9, 2)
    out["transfer_img_s"] = round(img_s, 1)
    eps = measure_full_loop()
    out["loop_examples_per_sec_by_epoch"] = [round(e, 1) for e in eps]
    out["loop_examples_per_sec_steady"] = round(
        float(np.mean(eps[1:])), 1
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
