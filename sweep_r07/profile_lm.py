"""Per-op device-time attribution of the TransformerLM train step on
the real chip — the profiling subsystem working beyond CNNs, and the
LM step's roofline position (is the flash-attention LM compute- or
bandwidth-bound at long context?)."""

import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from zookeeper_tpu.core import configure
    from zookeeper_tpu.models import TransformerLM
    from zookeeper_tpu.parallel import DataParallelPartitioner
    from zookeeper_tpu.training import TrainState, make_train_step
    from zookeeper_tpu.training.profiling import (
        format_breakdown,
        op_time_breakdown,
    )

    seq, vocab, batch_size = 8192, 1024, 4
    model = TransformerLM()
    configure(
        model,
        {
            "num_layers": 4, "d_model": 512, "num_heads": 8,
            "max_seq_len": seq, "compute_dtype": "bfloat16",
        },
        name="model",
    )
    module = model.build((seq,), num_classes=vocab)
    params, mstate = model.initialize(module, (seq,))
    ts = TrainState.create(
        apply_fn=module.apply, params=params, model_state=mstate,
        tx=optax.adam(1e-3),
    )
    part = DataParallelPartitioner()
    configure(part, {}, name="p")
    part.setup()
    ts = part.shard_state(ts)
    step = part.compile_step(make_train_step(), ts)

    rng = np.random.default_rng(0)
    batch = jax.device_put(
        {
            "input": jnp.asarray(
                rng.integers(0, vocab, (batch_size, seq)), jnp.int32
            ),
            "target": jnp.asarray(
                rng.integers(0, vocab, (batch_size, seq)), jnp.int32
            ),
        },
        part.batch_sharding(),
    )
    for _ in range(3):
        ts, metrics = step(ts, batch)
    float(jax.device_get(metrics["loss"]))

    steps = 10
    trace_dir = tempfile.mkdtemp(prefix="zk_trace_lm_")
    with jax.profiler.trace(trace_dir):
        for _ in range(steps):
            ts, metrics = step(ts, batch)
        float(jax.device_get(metrics["loss"]))
    print(
        f"model=TransformerLM 4L d512 h8 s{seq} b{batch_size} bf16 flash"
    )
    print(format_breakdown(op_time_breakdown(trace_dir, steps=steps)))


if __name__ == "__main__":
    main()
