"""Fleet serving: a prefix-affinity router over N decode replicas
(docs/DESIGN.md §23).

One process serves one mesh; the north star's millions-of-users
traffic needs N replicas behind a front door. This module is that
front door: a :class:`FleetRouter` over worker processes (each a
:class:`~zookeeper_tpu.serving.decode.service.LMServingConfig` behind
a small HTTP seam — ``zookeeper_tpu.testing.spawn_fleet_workers``
spawns real ones on CPU) that turns the §20 radix prefix cache from a
per-box optimization into a fleet-wide one:

- **Prefix-affinity scheduling** — the router keeps one pageless
  :class:`~zookeeper_tpu.serving.decode.prefix_key.PrefixIndex` per
  replica (the EXACT chunking/keying the replica's real
  ``RadixPrefixCache`` trie uses — shared code, not a reimplementation)
  and routes each prompt to the replica predicted to hold the most of
  it warm, falling back by load (router-side in-flight count, worker
  queue depth and ``zk_kv_pool_free_pages`` scraped from each
  replica's live ``/metrics``) when nobody is warm.
- **Session continuity** — a multi-turn conversation pins to its
  replica (``session=`` on submit), so turn-2+ re-enters that
  replica's radix cache and rides the §20 warm-prefill path instead of
  re-prefilling its whole history on a cold box. Pins persist to
  ``state_path`` (atomic write) so a restarted router keeps sessions
  warm.
- **Health + failure semantics** — ``/healthz``-probed replicas; a
  dead worker's in-flight requests fail clean with
  :class:`~zookeeper_tpu.serving.batcher.WorkerCrashedError` (the §10
  posture), its prefix index drops (a restarted worker is cold), and
  its sessions re-route cold to a survivor on their next turn.
  ``FaultPlan.fleet_replica_kill_at`` / ``fleet_router_restart_at``
  are the deterministic chaos coordinates.
- **Cross-process observability** — the router mints the rid
  (:func:`~zookeeper_tpu.observability.requests.next_rid`) and the
  worker's scheduler ADOPTS it (``submit(rid=...)``), so one request
  is traceable end-to-end: the router's ``RequestLog("fleet")`` and
  ``fleet_route`` flow events on one side, the worker's RequestLog /
  trace on the other, joined on the rid. :class:`FleetMetrics` renders
  the ``zk_fleet_*`` family and :meth:`FleetRouter.status` is the
  ``/statusz`` fleet section.

The router is transport-agnostic: the default transport POSTs JSON to
each worker's ``/generate`` endpoint, and tests inject in-process
transports to pin routing policy without spawning processes — the
multi-process certification lives in ``tests/serving/test_fleet.py``.
"""

import json
import logging
import os
import signal
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from zookeeper_tpu.observability import trace as _trace
from zookeeper_tpu.observability.registry import (
    DEFAULT_MS_BUCKETS,
    MetricsRegistry,
)
from zookeeper_tpu.observability.requests import RequestLog, next_rid
from zookeeper_tpu.serving.batcher import WorkerCrashedError
from zookeeper_tpu.serving.decode.prefix_key import PrefixIndex
from zookeeper_tpu.serving.guardrails import CircuitBreaker

logger = logging.getLogger(__name__)

__all__ = [
    "FleetMetrics",
    "FleetResponse",
    "FleetRouter",
    "FleetUnavailableError",
    "ReplicaHandle",
]


class FleetUnavailableError(RuntimeError):
    """No healthy replica is left to route to (every worker dead or
    none configured) — the fleet-level analogue of a dead worker."""


class FleetResponse:
    """One routed generation: the worker's reply plus the routing
    decision that produced it (the per-request affinity audit trail)."""

    __slots__ = (
        "rid",
        "worker_id",
        "tokens",
        "ttft_ms",
        "shared_tokens",
        "finish_reason",
        "affinity_hit",
        "rerouted",
        "predicted_shared",
    )

    def __init__(
        self,
        *,
        rid: int,
        worker_id: str,
        tokens: np.ndarray,
        ttft_ms: Optional[float],
        shared_tokens: int,
        finish_reason: Optional[str],
        affinity_hit: bool,
        rerouted: bool,
        predicted_shared: int,
    ) -> None:
        self.rid = rid
        self.worker_id = worker_id
        self.tokens = tokens
        self.ttft_ms = ttft_ms
        self.shared_tokens = shared_tokens
        self.finish_reason = finish_reason
        self.affinity_hit = affinity_hit
        self.rerouted = rerouted
        self.predicted_shared = predicted_shared


class ReplicaHandle:
    """One worker the router fronts: its endpoints, liveness, the
    router-side load estimate, and its pageless prefix index."""

    def __init__(
        self,
        worker_id: str,
        generate_url: str,
        obs_url: Optional[str] = None,
        pid: Optional[int] = None,
    ) -> None:
        self.worker_id = str(worker_id)
        self.generate_url = generate_url
        self.obs_url = obs_url
        self.pid = pid
        self.healthy = True
        #: Router-side in-flight request count (the load term no
        #: scrape can race).
        self.outstanding = 0
        self.routed_total = 0
        self.index: Optional[PrefixIndex] = None  # attached by router
        #: Per-replica circuit breaker (attached by router; None until
        #: then — docs/DESIGN.md §24).
        self.breaker: Optional[CircuitBreaker] = None
        # Last /metrics scrape: (monotonic ts, queue_depth, free_pages).
        # Invalidated on every health-state TRANSITION so routing never
        # prefers a corpse (or a cold revival) on cached numbers.
        self._scrape: Optional[tuple] = None

    @classmethod
    def from_worker(cls, worker: Dict[str, Any]) -> "ReplicaHandle":
        """Build from a ``spawn_fleet_workers`` ready document."""
        return cls(
            worker["worker_id"],
            "http://127.0.0.1:%d/generate" % worker["generate_port"],
            obs_url="http://127.0.0.1:%d" % worker["metrics_port"],
            pid=worker.get("pid"),
        )


def _http_transport(
    replica: ReplicaHandle, payload: Dict[str, Any], timeout_s: float
) -> Dict[str, Any]:
    """Default transport: POST JSON to the worker's ``/generate``."""
    req = urllib.request.Request(
        replica.generate_url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())


def _http_health(replica: ReplicaHandle, timeout_s: float) -> bool:
    """Default health probe: the cheap ``/healthz`` liveness endpoint
    (constant body, no registry lock — the router pays nothing like
    the full ``/metrics`` exposition cost per probe)."""
    if replica.obs_url is None:
        return replica.healthy
    try:
        with urllib.request.urlopen(
            replica.obs_url + "/healthz", timeout=timeout_s
        ) as resp:
            return resp.status == 200
    except (urllib.error.URLError, OSError):
        return False


def _default_kill(replica: ReplicaHandle) -> None:
    """Chaos hook: SIGKILL the replica's OS process (the §23
    replica-death injection — a real process death, not a simulation)."""
    if replica.pid is None:
        raise RuntimeError(
            f"replica {replica.worker_id} has no pid to kill; inject a "
            "kill_replica hook for in-process transports."
        )
    os.kill(int(replica.pid), signal.SIGKILL)


class FleetMetrics:
    """The ``zk_fleet_*`` family on its own registry (attach it to an
    :class:`~zookeeper_tpu.observability.export.ObservabilityServer`
    next to the default registry, like ``DecodeMetrics.registry``):
    per-replica routed / affinity-hit counters + health gauges,
    fleet-wide re-route / crash counters, a routing-decision latency
    histogram, and session/replica-count gauges."""

    def __init__(self) -> None:
        self.registry = MetricsRegistry()
        self._routed: Dict[str, Any] = {}
        self._affinity: Dict[str, Any] = {}
        self._healthy: Dict[str, Any] = {}
        self._breaker: Dict[str, Any] = {}
        self._rerouted = self.registry.counter(
            "zk_fleet_rerouted_total",
            help="sessions re-routed cold off a dead replica",
        )
        self._crashes = self.registry.counter(
            "zk_fleet_worker_crashes_total",
            help="requests failed by a replica death mid-flight",
        )
        self._retries = self.registry.counter(
            "zk_fleet_retries_total",
            help="rid-preserving re-routes of requests that failed "
            "before their first token",
        )
        self._replicas = self.registry.gauge(
            "zk_fleet_replicas", help="configured replicas"
        )
        self._sessions = self.registry.gauge(
            "zk_fleet_sessions", help="live session pins"
        )
        self._route_ms = self.registry.histogram(
            "zk_fleet_route_ms",
            buckets=DEFAULT_MS_BUCKETS,
            help="routing-decision latency (choose + index update)",
        )
        # Exact-percentile window next to the fixed-bucket histogram
        # (the DecodeMetrics posture).
        self._route_samples: List[float] = []

    def _per_replica(self, table, name, help_, worker_id, cls="counter"):
        inst = table.get(worker_id)
        if inst is None:
            factory = (
                self.registry.counter
                if cls == "counter"
                else self.registry.gauge
            )
            inst = factory(name, help=help_, labels={"replica": worker_id})
            table[worker_id] = inst
        return inst

    def record_routed(
        self, worker_id: str, *, affinity_hit: bool, route_ms: float
    ) -> None:
        self._per_replica(
            self._routed,
            "zk_fleet_routed_total",
            "requests routed to this replica",
            worker_id,
        ).inc()
        if affinity_hit:
            self._per_replica(
                self._affinity,
                "zk_fleet_affinity_hits_total",
                "requests routed by warm-prefix affinity or session pin",
                worker_id,
            ).inc()
        self._route_ms.observe(float(route_ms))
        self._route_samples.append(float(route_ms))
        if len(self._route_samples) > 4096:
            del self._route_samples[:2048]

    def record_rerouted(self) -> None:
        self._rerouted.inc()

    def record_worker_crash(self) -> None:
        self._crashes.inc()

    def record_retry(self) -> None:
        self._retries.inc()

    def record_breaker_state(self, worker_id: str, code: float) -> None:
        """Per-replica breaker gauge: 0 closed, 0.5 half-open, 1 open."""
        self._per_replica(
            self._breaker,
            "zk_fleet_breaker_state",
            "circuit breaker state (0 closed, 0.5 half-open, 1 open)",
            worker_id,
            cls="gauge",
        ).set(float(code))

    def record_health(self, worker_id: str, healthy: bool) -> None:
        self._per_replica(
            self._healthy,
            "zk_fleet_replica_healthy",
            "1 = replica passed its last health probe",
            worker_id,
            cls="gauge",
        ).set(1.0 if healthy else 0.0)

    def set_replicas(self, n: int) -> None:
        self._replicas.set(float(n))

    def set_sessions(self, n: int) -> None:
        self._sessions.set(float(n))

    def snapshot(self) -> Dict[str, float]:
        out: Dict[str, float] = {
            "fleet_rerouted_total": self._rerouted.value,
            "fleet_worker_crashes_total": self._crashes.value,
            "fleet_retries_total": self._retries.value,
        }
        for wid, inst in self._routed.items():
            out[f"fleet_routed_total_{wid}"] = inst.value
        for wid, inst in self._affinity.items():
            out[f"fleet_affinity_hits_total_{wid}"] = inst.value
        if self._route_samples:
            out["fleet_route_ms_p50"] = float(
                np.percentile(self._route_samples, 50)
            )
            out["fleet_route_ms_p99"] = float(
                np.percentile(self._route_samples, 99)
            )
        return out


class FleetRouter:
    """The front door (see module docstring). Thread-safe: routing
    state mutates under one lock; worker POSTs run outside it (the
    scheduler's dispatch-outside-the-lock discipline), so concurrent
    submitters only serialize on the DECISION, never on generation."""

    def __init__(
        self,
        replicas: Sequence[ReplicaHandle],
        *,
        page_size: int,
        policy: str = "affinity",
        state_path: Optional[str] = None,
        request_timeout_s: float = 120.0,
        health_timeout_s: float = 2.0,
        scrape_ttl_s: float = 1.0,
        metrics: Optional[FleetMetrics] = None,
        transport: Optional[Callable[..., Dict[str, Any]]] = None,
        health_probe: Optional[Callable[..., bool]] = None,
        kill_replica: Optional[Callable[[ReplicaHandle], None]] = None,
        max_retries: int = 0,
        retry_backoff_s: float = 0.05,
        breaker_failures: int = 3,
        breaker_latency_ms: float = 0.0,
        breaker_latency_window: int = 3,
        breaker_cooldown_s: float = 5.0,
        breaker_jitter_frac: float = 0.5,
        breaker_seed: int = 0,
        breaker_clock: Optional[Callable[[], float]] = None,
        sleep: Optional[Callable[[float], None]] = None,
    ) -> None:
        if policy not in ("affinity", "round_robin"):
            raise ValueError(
                f"policy={policy!r}: expected 'affinity' or 'round_robin'."
            )
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica.")
        ids = [r.worker_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica worker_ids: {ids}")
        self.replicas: List[ReplicaHandle] = list(replicas)
        self.page_size = int(page_size)
        self.policy = policy
        self.state_path = state_path
        self.request_timeout_s = float(request_timeout_s)
        self.health_timeout_s = float(health_timeout_s)
        self.scrape_ttl_s = float(scrape_ttl_s)
        self.metrics = metrics if metrics is not None else FleetMetrics()
        self._transport = transport or _http_transport
        self._health_probe = health_probe or _http_health
        self._kill_replica_hook = kill_replica or _default_kill
        if max_retries < 0 or retry_backoff_s < 0:
            raise ValueError(
                f"max_retries={max_retries} and retry_backoff_s="
                f"{retry_backoff_s} must be >= 0."
            )
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self._sleep = sleep or time.sleep
        self.request_log = RequestLog("fleet")
        self._lock = threading.RLock()
        self._by_id = {r.worker_id: r for r in self.replicas}
        for r in self.replicas:
            r.index = PrefixIndex(self.page_size)
            # One breaker per replica (docs/DESIGN.md §24);
            # breaker_failures=0 + breaker_latency_ms=0 leaves it
            # permanently closed (trip conditions disabled).
            r.breaker = CircuitBreaker(
                key=r.worker_id,
                failure_threshold=breaker_failures,
                latency_threshold_ms=breaker_latency_ms,
                latency_window=breaker_latency_window,
                cooldown_s=breaker_cooldown_s,
                jitter_frac=breaker_jitter_frac,
                seed=breaker_seed,
                clock=breaker_clock,
            )
        #: session -> worker_id pins (the continuity contract).
        self._sessions: Dict[str, str] = {}
        self._rr_next = 0
        self.routed_total = 0
        self.affinity_hits_total = 0
        self.rerouted_total = 0
        self.retries_total = 0
        self._obs_server = None
        self._health_thread: Optional[threading.Thread] = None
        self._health_stop = threading.Event()
        self.metrics.set_replicas(len(self.replicas))
        for r in self.replicas:
            self.metrics.record_health(r.worker_id, r.healthy)
        if state_path and os.path.exists(state_path):
            self._load_state()

    # -- session-pin persistence (router restart recovery) ---------------

    def _load_state(self) -> None:
        """Adopt the previous router's session pins (restart recovery:
        pinned sessions stay on their WARM replica; the prefix indexes
        rebuild lazily from subsequent traffic — until they rewarm,
        unpinned traffic routes by load, which is correct, just cold)."""
        try:
            with open(self.state_path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning(
                "fleet state %s unreadable (%s); starting with no "
                "session pins", self.state_path, e,
            )
            return
        restored = {
            str(sid): str(wid)
            for sid, wid in doc.get("sessions", {}).items()
            if str(wid) in self._by_id
        }
        self._sessions.update(restored)
        self.metrics.set_sessions(len(self._sessions))
        if restored:
            logger.info(
                "fleet router restored %d session pin(s) from %s",
                len(restored), self.state_path,
            )

    def _save_state(self) -> None:
        """Atomic write (tmp + rename) so a router killed mid-save
        leaves the previous pins readable, never a torn file."""
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"sessions": dict(self._sessions)}, f)
        os.replace(tmp, self.state_path)

    # -- health ----------------------------------------------------------

    def check_health(self) -> Dict[str, bool]:
        """Probe every replica's ``/healthz`` once; a replica that
        fails the probe goes unhealthy (its sessions re-route on their
        next turn). Returns ``{worker_id: healthy}``. Call it
        explicitly (deterministic tests) or from the background thread
        (:meth:`start_health_checks`)."""
        out = {}
        for r in self.replicas:
            ok = bool(self._health_probe(r, self.health_timeout_s))
            with self._lock:
                if r.healthy and not ok:
                    self._mark_dead(r)
                elif ok and not r.healthy:
                    # A replica that comes BACK (restarted worker) is
                    # cold: serve it again, predict nothing warm. The
                    # pre-death scrape snapshot and breaker history die
                    # with the old process — a revival must not be
                    # load-ranked (or tripped) on the corpse's numbers.
                    r.healthy = True
                    r.index.clear()
                    r._scrape = None
                    if r.breaker is not None:
                        r.breaker.reset()
                        self.metrics.record_breaker_state(
                            r.worker_id, r.breaker.state_code()
                        )
                    self.metrics.record_health(r.worker_id, True)
                    logger.info(
                        "fleet replica %s healthy again (cold)",
                        r.worker_id,
                    )
            out[r.worker_id] = ok
        return out

    def start_health_checks(self, interval_s: float = 1.0) -> None:
        """Run :meth:`check_health` on a daemon thread every
        ``interval_s`` seconds until :meth:`close`."""
        if self._health_thread is not None:
            return
        self._health_stop.clear()

        def loop():
            while not self._health_stop.wait(interval_s):
                try:
                    self.check_health()
                except Exception:  # probes must never kill the thread
                    logger.exception("fleet health check failed")

        t = threading.Thread(
            target=loop, name="zk-fleet-health", daemon=True
        )
        t.start()
        self._health_thread = t

    def _mark_dead(self, replica: ReplicaHandle) -> None:
        """Caller holds the lock. The replica's index drops (its
        process — and with it every cached page — is gone; a restarted
        one is cold) and its health gauge goes to 0. Session pins are
        NOT dropped here: each re-pins to a survivor on its next turn
        (counted as a re-route), so the metric reflects re-routes that
        actually happened."""
        replica.healthy = False
        replica.index.clear()
        # Drop the cached load scrape WITH the health transition: the
        # TTL would otherwise keep serving the corpse's (often
        # flattering: it stopped queueing when it died) queue-depth
        # snapshot to the load fallback for up to scrape_ttl_s.
        replica._scrape = None
        self.metrics.record_health(replica.worker_id, False)
        logger.warning("fleet replica %s marked dead", replica.worker_id)

    # -- load fallback ---------------------------------------------------

    def _scrape_load(self, replica: ReplicaHandle):
        """Worker-side load terms from its live ``/metrics`` registry
        (``zk_decode_queue_depth``, ``zk_kv_pool_free_pages``), cached
        for ``scrape_ttl_s`` so a routing burst costs one scrape, not
        one per request. Returns ``(queue_depth, free_pages)`` —
        ``(0.0, 0.0)`` when the replica exposes no endpoint or the
        scrape fails (the router-side ``outstanding`` count still
        differentiates load)."""
        now = time.monotonic()
        cached = replica._scrape
        if cached is not None and now - cached[0] < self.scrape_ttl_s:
            return cached[1], cached[2]
        queue_depth, free_pages = 0.0, 0.0
        if replica.obs_url is not None:
            try:
                with urllib.request.urlopen(
                    replica.obs_url + "/metrics",
                    timeout=self.health_timeout_s,
                ) as resp:
                    body = resp.read().decode()
                for line in body.splitlines():
                    if line.startswith("zk_decode_queue_depth "):
                        queue_depth = float(line.split()[-1])
                    elif line.startswith("zk_kv_pool_free_pages "):
                        free_pages = float(line.split()[-1])
            except (urllib.error.URLError, OSError, ValueError):
                pass
        replica._scrape = (now, queue_depth, free_pages)
        return queue_depth, free_pages

    def _load_key(self, replica: ReplicaHandle):
        """Sort key for the load fallback: fewest in-flight + queued
        requests first; ties break toward the most free KV pages (the
        replica with headroom absorbs the next long prompt)."""
        queue_depth, free_pages = self._scrape_load(replica)
        return (replica.outstanding + queue_depth, -free_pages)

    # -- routing ---------------------------------------------------------

    def _route(self, tokens, session: Optional[str]):
        """The routing decision (caller holds no lock; takes it).
        Returns ``(replica, affinity_hit, rerouted, predicted)``."""
        with self._lock:
            healthy = [r for r in self.replicas if r.healthy]
            if not healthy:
                raise FleetUnavailableError(
                    f"no healthy replica left out of "
                    f"{len(self.replicas)} — every worker is dead."
                )
            chosen: Optional[ReplicaHandle] = None
            affinity_hit = False
            rerouted = False
            predicted = 0
            if session is not None and session in self._sessions:
                pinned = self._by_id.get(self._sessions[session])
                pin_ok = (
                    pinned is not None
                    and pinned.healthy
                    and (
                        pinned.breaker is None
                        or pinned.breaker.state
                        == CircuitBreaker.CLOSED
                        # An open-but-due pinned replica may serve its
                        # own probe: session continuity IS the cheapest
                        # probe traffic we have.
                        or pinned.breaker.try_probe()
                    )
                )
                if pin_ok:
                    # Session continuity: the pin IS the affinity —
                    # turn-2+ re-enters this replica's radix cache.
                    chosen = pinned
                    affinity_hit = True
                    predicted = pinned.index.predict(tokens)
                else:
                    # The pinned replica died (or its breaker opened):
                    # this turn re-routes COLD to a survivor and
                    # re-pins there.
                    rerouted = True
                    self.rerouted_total += 1
                    self.metrics.record_rerouted()
            if chosen is None:
                # Half-open probes take absolute priority: exactly one
                # request per cooldown tests a tripped replica, so it
                # must not starve behind closed-breaker candidates.
                chosen = next(
                    (
                        r
                        for r in healthy
                        if r.breaker is not None and r.breaker.try_probe()
                    ),
                    None,
                )
            if chosen is None:
                candidates = [
                    r
                    for r in healthy
                    if r.breaker is None
                    or r.breaker.state == CircuitBreaker.CLOSED
                ]
                if not candidates:
                    raise FleetUnavailableError(
                        f"all {len(healthy)} healthy replicas have open "
                        "circuit breakers — backing off until a "
                        "half-open probe succeeds."
                    )
                if self.policy == "round_robin":
                    chosen = candidates[self._rr_next % len(candidates)]
                    self._rr_next += 1
                else:
                    scored = [
                        (r.index.predict(tokens), r) for r in candidates
                    ]
                    best = max(p for p, _ in scored)
                    if best > 0:
                        # Warm-prefix affinity: the replica predicted
                        # to hold the most of this prompt resident.
                        chosen = max(
                            scored,
                            key=lambda pr: (
                                pr[0],
                                # Ties route by load, cheapest first.
                                tuple(-x for x in self._load_key(pr[1])),
                            ),
                        )[1]
                        affinity_hit = True
                        predicted = best
                    else:
                        # Nobody is warm: pure load fallback.
                        chosen = min(candidates, key=self._load_key)
            if session is not None:
                if self._sessions.get(session) != chosen.worker_id:
                    self._sessions[session] = chosen.worker_id
                    self._save_state()
                self.metrics.set_sessions(len(self._sessions))
            # Predict the replica's FUTURE warm state: the worker
            # inserts this prompt's pages into its radix cache after
            # prefill, so the index observes exactly that.
            chosen.index.observe(tokens)
            chosen.routed_total += 1
            self.routed_total += 1
            if affinity_hit:
                self.affinity_hits_total += 1
            for r in self.replicas:
                if r.breaker is not None:
                    self.metrics.record_breaker_state(
                        r.worker_id, r.breaker.state_code()
                    )
            return chosen, affinity_hit, rerouted, predicted

    def submit(
        self,
        tokens: Any,
        *,
        session: Optional[str] = None,
        max_new_tokens: int = 16,
        rid: Optional[int] = None,
    ) -> FleetResponse:
        """Route one prompt and block for its generation. ``session``
        pins multi-turn conversations to one replica; ``rid`` defaults
        to a freshly-minted router id the WORKER adopts (one id across
        both processes). Raises :class:`WorkerCrashedError` when the
        chosen replica dies mid-request (the caller may resubmit — the
        dead replica is already unhealthy, so the retry re-routes) and
        :class:`FleetUnavailableError` when nobody is left."""
        from zookeeper_tpu.resilience import faults

        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 1 or tokens.shape[0] < 1:
            raise ValueError(
                f"prompt must be a non-empty 1-D int token array, got "
                f"shape {tokens.shape}."
            )
        rid = next_rid() if rid is None else int(rid)
        t_submit_ns = time.perf_counter_ns()
        token_list = [int(x) for x in tokens.tolist()]
        retries = 0
        while True:
            t0 = time.perf_counter()
            chosen, affinity_hit, rerouted, predicted = self._route(
                token_list, session
            )
            route_ms = (time.perf_counter() - t0) * 1e3
            self.metrics.record_routed(
                chosen.worker_id,
                affinity_hit=affinity_hit,
                route_ms=route_ms,
            )
            if _trace.enabled():
                _trace.event(
                    "fleet_route",
                    rid=rid,
                    attrs={
                        "replica": chosen.worker_id,
                        "affinity_hit": affinity_hit,
                        "rerouted": rerouted,
                        "predicted_shared": predicted,
                        "session": session or "",
                        "attempt": retries,
                    },
                )
            plan = faults.active()
            if plan is not None and plan.take_fleet_replica_kill():
                # Chaos coordinate (docs/DESIGN.md §23): the chosen
                # replica dies NOW — the forward below finds a dead
                # worker, exactly the mid-request death the contract
                # covers.
                self._kill_replica_hook(chosen)
            with self._lock:
                chosen.outstanding += 1
            t_fwd = time.perf_counter()
            try:
                payload = {
                    "tokens": token_list,
                    "max_new_tokens": int(max_new_tokens),
                    "rid": rid,
                    "session": session,
                }
                body = self._transport(
                    chosen, payload, self.request_timeout_s
                )
            except (urllib.error.URLError, OSError, ConnectionError) as e:
                with self._lock:
                    if chosen.breaker is not None:
                        chosen.breaker.record_failure()
                        self.metrics.record_breaker_state(
                            chosen.worker_id,
                            chosen.breaker.state_code(),
                        )
                    if chosen.healthy:
                        self._mark_dead(chosen)
                self.metrics.record_worker_crash()
                if retries < self.max_retries:
                    # Rid-preserving re-route. Safe at-most-once: this
                    # transport is blocking and non-streaming, so a
                    # connection-level failure means ZERO tokens
                    # reached the caller — nothing was delivered that
                    # a second attempt could duplicate.
                    retries += 1
                    self.retries_total += 1
                    self.metrics.record_retry()
                    if _trace.enabled():
                        _trace.event(
                            "fleet_retry",
                            rid=rid,
                            attrs={
                                "failed_replica": chosen.worker_id,
                                "attempt": retries,
                            },
                        )
                    logger.warning(
                        "fleet rid=%d attempt %d failed on %s — "
                        "retrying (%d/%d)",
                        rid,
                        retries,
                        chosen.worker_id,
                        retries,
                        self.max_retries,
                    )
                    self._sleep(
                        self.retry_backoff_s * (2 ** (retries - 1))
                    )
                    continue
                detail = f"WorkerCrashedError replica={chosen.worker_id}"
                if retries:
                    detail += f" retried={retries}"
                self.request_log.append(
                    rid,
                    "crashed",
                    enqueue_ns=t_submit_ns,
                    complete_ns=time.perf_counter_ns(),
                    detail=detail,
                    role="router",
                )
                raise WorkerCrashedError(
                    f"fleet replica {chosen.worker_id} died mid-request "
                    f"(rid={rid}, retried={retries}): {e}; the replica "
                    "is unhealthy — resubmit to re-route to a survivor."
                ) from e
            finally:
                with self._lock:
                    chosen.outstanding -= 1
            fwd_ms = (time.perf_counter() - t_fwd) * 1e3
            with self._lock:
                if chosen.breaker is not None:
                    # Worker-side error bodies also count as success
                    # here: the replica answered promptly — its
                    # failure is deterministic (bad request), not a
                    # replica-health signal.
                    chosen.breaker.record_success(fwd_ms)
                    self.metrics.record_breaker_state(
                        chosen.worker_id, chosen.breaker.state_code()
                    )
            break
        if "error" in body:
            self.request_log.append(
                rid,
                "error",
                enqueue_ns=t_submit_ns,
                complete_ns=time.perf_counter_ns(),
                detail=f"{body.get('type', 'error')} "
                f"replica={chosen.worker_id}",
                role="router",
            )
            raise RuntimeError(
                f"fleet replica {chosen.worker_id} failed rid={rid}: "
                f"{body.get('type', 'error')}: {body['error']}"
            )
        out = np.asarray(body["tokens"], np.int32)
        self.request_log.append(
            rid,
            "ok",
            enqueue_ns=t_submit_ns,
            complete_ns=time.perf_counter_ns(),
            tokens=int(out.shape[0]),
            detail=(
                f"replica={chosen.worker_id} "
                f"shared={int(body.get('shared_tokens', 0))} "
                f"predicted={predicted}"
                + (f" retried={retries}" if retries else "")
            ),
            role="router",
        )
        return FleetResponse(
            rid=rid,
            worker_id=chosen.worker_id,
            tokens=out,
            ttft_ms=body.get("ttft_ms"),
            shared_tokens=int(body.get("shared_tokens", 0)),
            finish_reason=body.get("finish_reason"),
            affinity_hit=affinity_hit,
            rerouted=rerouted,
            predicted_shared=predicted,
        )

    # -- observability ---------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The ``/statusz`` fleet section: policy, per-replica health/
        load/affinity state, session pins, routing totals."""
        with self._lock:
            return {
                "policy": self.policy,
                "replicas": [
                    {
                        "worker_id": r.worker_id,
                        "healthy": r.healthy,
                        "outstanding": r.outstanding,
                        "routed_total": r.routed_total,
                        "index_nodes": r.index.nodes if r.index else 0,
                        "generate_url": r.generate_url,
                        "breaker": (
                            r.breaker.status()
                            if r.breaker is not None
                            else None
                        ),
                    }
                    for r in self.replicas
                ],
                "healthy_replicas": sum(
                    1 for r in self.replicas if r.healthy
                ),
                "sessions": len(self._sessions),
                "routed_total": self.routed_total,
                "affinity_hits_total": self.affinity_hits_total,
                "rerouted_total": self.rerouted_total,
                "retries_total": self.retries_total,
                "max_retries": self.max_retries,
                "retry_backoff_s": self.retry_backoff_s,
                "state_path": self.state_path,
            }

    def session_pin(self, session: str) -> Optional[str]:
        """The replica ``session`` is pinned to (None = unpinned)."""
        with self._lock:
            return self._sessions.get(str(session))

    def start_observability(self, port: int = 0):
        """Serve the router's own ``/metrics`` (``zk_fleet_*``) +
        ``/statusz`` (fleet + requests sections) + ``/healthz``."""
        from zookeeper_tpu.observability import ObservabilityServer
        from zookeeper_tpu.observability.registry import default_registry

        server = ObservabilityServer(
            [default_registry(), self.metrics.registry],
            port=port,
            status_providers={
                "fleet": self.status,
                "requests": self.request_log.as_status,
            },
        )
        server.start()
        self._obs_server = server
        return server

    @property
    def obs_server(self):
        return self._obs_server

    def close(self) -> None:
        """Stop the health thread and the observability endpoint (the
        workers are NOT stopped — their lifecycle belongs to whoever
        spawned them)."""
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)
            self._health_thread = None
        if self._obs_server is not None:
            self._obs_server.stop()
            self._obs_server = None
