"""Runtime overload defenses (docs/DESIGN.md §24).

The observability stack measures overload and the fleet router routes
around dead replicas; this module is what *defends* the system while
that is happening — the runtime half of the §24 guardrails story (the
judging half is ``zookeeper_tpu.loadgen``):

- :class:`OverloadGuard` — predicted-miss admission. Two EWMA
  estimators (queue wait observed enqueue→dispatch; service time per
  generated unit observed dispatch→complete) predict a submit's
  completion time against its deadline; a request predicted to miss is
  shed AT SUBMIT with :class:`PredictedMissError` instead of occupying
  queue + device time only to expire anyway. The PR 4 invariant holds
  verbatim: an empty queue always admits one request, and a request
  with no deadline has nothing to miss.
- :class:`CircuitBreaker` — the per-replica state machine the
  :class:`~zookeeper_tpu.serving.fleet.FleetRouter` wraps around each
  worker: ``closed`` → ``open`` on a consecutive-failure or
  consecutive-slow-success threshold → ``half_open`` after a jittered
  cooldown (exactly ONE probe request rides through) → ``closed`` on
  probe success / back to ``open`` on probe failure. The latency trip
  is the case the existing ``/healthz`` liveness probe cannot see: a
  gray-failed replica answers probes instantly while poisoning every
  real request routed to it.
- :class:`BrownOut` — sustained-pressure degradation: after
  ``engage_after`` consecutive predicted-miss sheds the service caps
  ``max_new_tokens`` and disables speculation for newly admitted
  streams; the state TRANSITION applies only at the PR 9 drain
  boundary (empty slot array — the same boundary weight hot-swaps wait
  for) so in-flight sequences are untouched. Loudly logged both ways;
  auto-recovers after ``release_after`` consecutive admits.

Every knob is deterministic and clock-injectable: breaker cooldown
jitter draws from the splitmix64 counter RNG
(:class:`~zookeeper_tpu.data.augrng.AugRng`) keyed on ``(seed, replica
key, open count)`` — never ``random`` / wall entropy — so two runs of
the same chaos plan open and probe at identical offsets.
"""

import logging
import threading
import time
import zlib
from typing import Callable, Dict, Optional, Tuple

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.data.augrng import AugRng
from zookeeper_tpu.observability.registry import (
    DEFAULT_MS_BUCKETS,
    MetricsRegistry,
)
from zookeeper_tpu.serving.batcher import RejectedError

logger = logging.getLogger(__name__)

__all__ = [
    "BrownOut",
    "CircuitBreaker",
    "OverloadGuard",
    "PredictedMissError",
]


class PredictedMissError(RejectedError):
    """Predicted-miss admission shed: the EWMA cost model predicts this
    request would expire before completion, so it was shed AT SUBMIT
    instead of wasting queue + device time. A :class:`RejectedError`
    subclass, so existing shed handling (``outcome_of`` → ``"shed"``,
    client backoff) applies unchanged; the distinct type lets callers
    and the RequestLog ``detail`` tell predictive sheds from static
    ``shed_above`` ones."""


class BrownOut:
    """Consecutive-pressure hysteresis: ``engage_after`` predicted-miss
    sheds in a row engage; ``release_after`` admits in a row release.
    Pure bookkeeping — the OWNING service applies the actual
    degradation (cap ``max_new_tokens``, skip speculation) at its own
    safe boundary. Thread-safe."""

    def __init__(self, engage_after: int, release_after: int) -> None:
        if engage_after < 1 or release_after < 1:
            raise ValueError(
                f"engage_after={engage_after} and release_after="
                f"{release_after} must be >= 1."
            )
        self.engage_after = int(engage_after)
        self.release_after = int(release_after)
        self.engaged = False
        self.engaged_total = 0
        self._shed_streak = 0
        self._ok_streak = 0
        self._lock = threading.Lock()

    def note(self, shed: bool) -> None:
        """Record one admission decision (True = predicted-miss shed)."""
        with self._lock:
            if shed:
                self._shed_streak += 1
                self._ok_streak = 0
                if (
                    not self.engaged
                    and self._shed_streak >= self.engage_after
                ):
                    self.engaged = True
                    self.engaged_total += 1
            else:
                self._ok_streak += 1
                self._shed_streak = 0
                if self.engaged and self._ok_streak >= self.release_after:
                    self.engaged = False

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "engaged": self.engaged,
                "engaged_total": self.engaged_total,
                "shed_streak": self._shed_streak,
                "ok_streak": self._ok_streak,
                "engage_after": self.engage_after,
                "release_after": self.release_after,
            }


@component
class OverloadGuard:
    """Predicted-miss admission (see module docstring).

    The math, per submit with ``queued_units`` of work ahead of it,
    ``request_units`` of its own, and ``deadline_ms`` remaining::

        predicted_ms = max(queued_units * service_ewma, wait_ewma)
                       + request_units * service_ewma
        shed iff queued_units > 0 and predicted_ms > deadline_ms * headroom

    ``service_ewma`` is the EWMA of observed per-unit service time
    (dispatch→complete over delivered units); ``wait_ewma`` is the EWMA
    of observed whole-request queue waits (enqueue→dispatch) and acts
    as a floor — when real waits exceed the queue×service model (batch
    coalescing gaps, dispatch stalls), the floor catches what the
    product term misses. Units are the caller's: generated tokens for
    the decode scheduler, rows for the MicroBatcher — the estimator
    only ever divides and multiplies consistently.

    Fail-open by construction: below ``min_samples`` observations the
    guard admits everything (a cold estimator must not shed), an empty
    queue always admits (the PR 4 invariant — there is no wait to
    predict), and a request without a deadline has nothing to miss.
    """

    #: Master switch — a disabled guard admits everything and records
    #: nothing (services treat ``guard=None`` and ``enabled=False``
    #: identically).
    enabled: bool = Field(False)
    #: EWMA smoothing factor for both estimators (1.0 = last sample
    #: only).
    alpha: float = Field(0.25)
    #: Completed-request observations required before the guard may
    #: shed (warmup admits all — a cold estimator is a guess).
    min_samples: int = Field(4)
    #: Shed when ``predicted > headroom * deadline``; > 1.0 sheds
    #: later (tolerates estimator optimism), < 1.0 sheds earlier.
    headroom: float = Field(1.0)
    #: Consecutive predicted-miss sheds that engage brown-out
    #: (0 = brown-out off).
    brownout_after: int = Field(0)
    #: Consecutive admits that release an engaged brown-out.
    brownout_release: int = Field(16)
    #: ``max_new_tokens`` cap applied to newly admitted streams while
    #: browned out.
    brownout_max_new_tokens: int = Field(8)

    # -- wiring ----------------------------------------------------------

    def bind(self) -> "OverloadGuard":
        if not 0.0 < self.alpha <= 1.0:
            raise ValueError(f"alpha={self.alpha} must be in (0, 1].")
        if self.min_samples < 1:
            raise ValueError(
                f"min_samples={self.min_samples} must be >= 1."
            )
        if self.headroom <= 0.0:
            raise ValueError(f"headroom={self.headroom} must be > 0.")
        if self.brownout_max_new_tokens < 1:
            raise ValueError(
                f"brownout_max_new_tokens={self.brownout_max_new_tokens} "
                "must be >= 1."
            )
        object.__setattr__(self, "_lock", threading.Lock())
        object.__setattr__(self, "_service_ewma", None)
        object.__setattr__(self, "_wait_ewma", None)
        object.__setattr__(self, "_samples", 0)
        object.__setattr__(
            self,
            "_brownout",
            BrownOut(self.brownout_after, self.brownout_release)
            if self.brownout_after > 0
            else None,
        )
        return self

    def _require_bound(self) -> None:
        if getattr(self, "_lock", None) is None:
            raise RuntimeError(
                "OverloadGuard is not bound: call guard.bind() before "
                "use."
            )

    # -- metrics (zk_guard_* family, own registry — the DecodeMetrics
    # posture: attach it to an ObservabilityServer next to the default
    # registry) -----------------------------------------------------------

    def _obs(self) -> dict:
        from zookeeper_tpu.serving.metrics import _get_or_build_obs

        return _get_or_build_obs(self, self._build_obs)

    def _build_obs(self) -> dict:
        registry = MetricsRegistry()
        return {
            "registry": registry,
            "counters": {
                "predicted_miss": registry.counter(
                    "zk_guard_predicted_miss_total",
                    help="submits shed by predicted-miss admission",
                ),
                "admitted": registry.counter(
                    "zk_guard_admitted_total",
                    help="submits the guard admitted",
                ),
                "brownouts": registry.counter(
                    "zk_guard_brownouts_total",
                    help="brown-out engagements applied at the drain "
                    "boundary",
                ),
            },
            "gauges": {
                "service_ewma_ms": registry.gauge(
                    "zk_guard_service_ewma_ms",
                    help="EWMA per-unit service time estimate",
                ),
                "wait_ewma_ms": registry.gauge(
                    "zk_guard_wait_ewma_ms",
                    help="EWMA whole-request queue wait estimate",
                ),
                "brownout_active": registry.gauge(
                    "zk_guard_brownout_active",
                    help="1 = brown-out degradation applied (cap + no "
                    "speculation for new admissions)",
                ),
            },
            "hist": {
                "predicted_ms": registry.histogram(
                    "zk_guard_predicted_ms",
                    buckets=DEFAULT_MS_BUCKETS,
                    help="predicted completion time at admission",
                ),
            },
            "windows": {},
        }

    @property
    def registry(self) -> MetricsRegistry:
        return self._obs()["registry"]

    # -- estimators ------------------------------------------------------

    def observe_service(self, service_ms: float, units: int) -> None:
        """Feed one completed request's dispatch→complete time over the
        units it delivered (tokens / rows)."""
        self._require_bound()
        per_unit = float(service_ms) / max(1, int(units))
        with self._lock:
            cur = self._service_ewma
            object.__setattr__(
                self,
                "_service_ewma",
                per_unit
                if cur is None
                else cur + self.alpha * (per_unit - cur),
            )
            object.__setattr__(self, "_samples", self._samples + 1)
            self._obs()["gauges"]["service_ewma_ms"].set(
                self._service_ewma
            )

    def observe_wait(self, wait_ms: float) -> None:
        """Feed one completed request's enqueue→dispatch queue wait."""
        self._require_bound()
        with self._lock:
            cur = self._wait_ewma
            object.__setattr__(
                self,
                "_wait_ewma",
                float(wait_ms)
                if cur is None
                else cur + self.alpha * (float(wait_ms) - cur),
            )
            self._obs()["gauges"]["wait_ewma_ms"].set(self._wait_ewma)

    @property
    def samples(self) -> int:
        return getattr(self, "_samples", 0)

    def predicted_ms(
        self, queued_units: float, request_units: float
    ) -> Optional[float]:
        """The model's completion-time prediction (None while warming
        up — below ``min_samples`` the guard has no opinion)."""
        self._require_bound()
        with self._lock:
            if self._samples < self.min_samples:
                return None
            service = self._service_ewma or 0.0
            wait = self._wait_ewma or 0.0
        queue_ms = max(float(queued_units) * service, wait)
        return queue_ms + float(request_units) * service

    # -- admission -------------------------------------------------------

    def admit(
        self,
        *,
        queued_units: float,
        request_units: float,
        deadline_ms: Optional[float],
    ) -> Tuple[bool, Optional[float]]:
        """One admission decision: ``(admitted, predicted_ms)``.

        Records the decision (counters + brown-out pressure); the
        CALLER raises :class:`PredictedMissError` on False so it can
        stamp its own RequestLog summary / trace event first (the shed
        has no request handle — same shape as the static shed path).
        """
        self._require_bound()
        predicted = self.predicted_ms(queued_units, request_units)
        obs = self._obs()
        if predicted is not None:
            obs["hist"]["predicted_ms"].observe(predicted)
        shed = (
            predicted is not None
            # The PR 4 invariant: an empty queue always admits one
            # request — the guard predicts WAITING cost, and there is
            # none.
            and queued_units > 0
            # No deadline, nothing to miss.
            and deadline_ms is not None
            and predicted > float(deadline_ms) * self.headroom
        )
        obs["counters"]["predicted_miss" if shed else "admitted"].inc()
        brownout = getattr(self, "_brownout", None)
        if brownout is not None:
            brownout.note(shed)
        return (not shed), predicted

    # -- brown-out seam (the owning scheduler polls + applies) -----------

    @property
    def brownout_engaged(self) -> bool:
        """Whether pressure WANTS brown-out (the controller's state).
        The owning scheduler stages this into its actual degradation at
        the drain boundary — the two can briefly disagree while slots
        are occupied."""
        brownout = getattr(self, "_brownout", None)
        return brownout is not None and brownout.engaged

    def record_brownout_applied(self, active: bool) -> None:
        """The owning scheduler APPLIED a brown-out transition at its
        drain boundary: update the gauge (and count engagements)."""
        obs = self._obs()
        obs["gauges"]["brownout_active"].set(1.0 if active else 0.0)
        if active:
            obs["counters"]["brownouts"].inc()

    # -- introspection ---------------------------------------------------

    def status(self) -> Dict[str, object]:
        """The ``/statusz`` guardrails section."""
        if getattr(self, "_lock", None) is None:
            return {"enabled": False, "bound": False}
        with self._lock:
            service = self._service_ewma
            wait = self._wait_ewma
            samples = self._samples
        obs = self._obs()
        brownout = getattr(self, "_brownout", None)
        return {
            "enabled": bool(self.enabled),
            "samples": samples,
            "warmed_up": samples >= self.min_samples,
            "service_ewma_ms": (
                round(service, 4) if service is not None else None
            ),
            "wait_ewma_ms": round(wait, 4) if wait is not None else None,
            "headroom": self.headroom,
            "predicted_miss_total": int(
                obs["counters"]["predicted_miss"].value
            ),
            "admitted_total": int(obs["counters"]["admitted"].value),
            "brownout": (
                brownout.status()
                if brownout is not None
                else {"engaged": False, "configured": False}
            ),
        }

    def snapshot(self) -> Dict[str, float]:
        obs = self._obs()
        out = {
            "guard_predicted_miss_total": float(
                obs["counters"]["predicted_miss"].value
            ),
            "guard_admitted_total": float(
                obs["counters"]["admitted"].value
            ),
            "guard_brownouts_total": float(
                obs["counters"]["brownouts"].value
            ),
        }
        if getattr(self, "_service_ewma", None) is not None:
            out["guard_service_ewma_ms"] = float(self._service_ewma)
        if getattr(self, "_wait_ewma", None) is not None:
            out["guard_wait_ewma_ms"] = float(self._wait_ewma)
        return out


class CircuitBreaker:
    """Per-replica circuit breaker (see module docstring for the state
    machine). Plain class, one per :class:`ReplicaHandle`; the router
    drives it under its own lock but every method is independently
    thread-safe (probe claiming must be race-free even if a future
    transport records from its own thread).

    Trip conditions (either, measured on CONSECUTIVE results):

    - ``failure_threshold`` transport failures in a row (0 disables);
    - ``latency_window`` successes in a row slower than
      ``latency_threshold_ms`` (0.0 disables) — the gray-failure case
      a liveness probe cannot see.

    ``clock`` is injectable (defaults to ``time.monotonic``) and the
    cooldown jitter is a splitmix64 draw keyed on ``(seed, crc32(key),
    open count)``: in ``[cooldown_s, cooldown_s * (1 + jitter_frac)]``,
    deterministic per open, different across opens and replicas — the
    fleet's breakers never re-probe in lockstep after a correlated
    trip."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self,
        *,
        key: str = "",
        failure_threshold: int = 3,
        latency_threshold_ms: float = 0.0,
        latency_window: int = 3,
        cooldown_s: float = 5.0,
        jitter_frac: float = 0.5,
        seed: int = 0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        if failure_threshold < 0 or latency_window < 1:
            raise ValueError(
                f"failure_threshold={failure_threshold} must be >= 0 "
                f"(0 disables) and latency_window={latency_window} "
                ">= 1."
            )
        if cooldown_s <= 0 or jitter_frac < 0:
            raise ValueError(
                f"cooldown_s={cooldown_s} must be > 0 and jitter_frac="
                f"{jitter_frac} >= 0."
            )
        self.key = str(key)
        self.failure_threshold = int(failure_threshold)
        self.latency_threshold_ms = float(latency_threshold_ms)
        self.latency_window = int(latency_window)
        self.cooldown_s = float(cooldown_s)
        self.jitter_frac = float(jitter_frac)
        self.seed = int(seed)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._fail_streak = 0
        self._slow_streak = 0
        self._open_until = 0.0
        self.opened_total = 0
        self.probes_total = 0

    # -- reads -----------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def state_code(self) -> float:
        """Gauge encoding: 0 closed, 0.5 half-open, 1 open."""
        with self._lock:
            return {self.CLOSED: 0.0, self.HALF_OPEN: 0.5}.get(
                self._state, 1.0
            )

    @property
    def open_until(self) -> float:
        """When the next half-open probe becomes due (clock units;
        meaningful only while open)."""
        with self._lock:
            return self._open_until

    def routable(self) -> bool:
        """Whether a request may be routed here right now: closed, or
        open with the probe due (claim it with :meth:`try_probe`).
        Half-open means the single probe is already in flight — no
        second request rides along."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                return self._clock() >= self._open_until
            return False

    def try_probe(self) -> bool:
        """Claim THE half-open probe: True exactly once per cooldown
        expiry (open + due → half_open); every other caller gets False.
        The winner's next record_success/record_failure resolves the
        probe."""
        with self._lock:
            if (
                self._state == self.OPEN
                and self._clock() >= self._open_until
            ):
                self._state = self.HALF_OPEN
                self.probes_total += 1
                logger.info(
                    "circuit breaker %s half-open: probe in flight",
                    self.key or "<anon>",
                )
                return True
            return False

    # -- transitions -----------------------------------------------------

    def _trip(self, reason: str) -> None:
        """Caller holds the lock."""
        self._state = self.OPEN
        self.opened_total += 1
        # Deterministic jitter: splitmix64 keyed by (seed, replica,
        # open count) — no `random`, no wall entropy.
        rng = AugRng(
            self.seed, zlib.crc32(self.key.encode()), self.opened_total
        )
        delay = self.cooldown_s * (
            1.0 + rng.uniform(0.0, self.jitter_frac)
            if self.jitter_frac > 0
            else 1.0
        )
        self._open_until = self._clock() + delay
        self._fail_streak = 0
        self._slow_streak = 0
        logger.warning(
            "circuit breaker %s OPEN (%s); next probe in %.3fs",
            self.key or "<anon>", reason, delay,
        )

    def record_success(self, latency_ms: Optional[float] = None) -> None:
        """A request to this replica completed. While closed, a
        too-slow success still counts toward the latency trip; the
        half-open probe's success closes the breaker."""
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._state = self.CLOSED
                self._fail_streak = 0
                self._slow_streak = 0
                logger.info(
                    "circuit breaker %s CLOSED (probe succeeded)",
                    self.key or "<anon>",
                )
                return
            if self._state != self.CLOSED:
                return  # late result from before the trip
            self._fail_streak = 0
            if (
                self.latency_threshold_ms > 0
                and latency_ms is not None
                and float(latency_ms) > self.latency_threshold_ms
            ):
                self._slow_streak += 1
                if self._slow_streak >= self.latency_window:
                    self._trip(
                        f"{self._slow_streak} consecutive responses "
                        f"over {self.latency_threshold_ms:.0f}ms"
                    )
            else:
                self._slow_streak = 0

    def record_failure(self) -> None:
        """A request to this replica failed at the transport. The
        half-open probe's failure re-opens with a fresh jittered
        cooldown."""
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._trip("probe failed")
                return
            if self._state != self.CLOSED:
                return
            self._fail_streak += 1
            if (
                self.failure_threshold > 0
                and self._fail_streak >= self.failure_threshold
            ):
                self._trip(f"{self._fail_streak} consecutive failures")

    def reset(self) -> None:
        """Back to closed with clean streaks — the router calls this
        when a dead replica passes its health probe again (a restarted
        worker deserves a fresh breaker, not the corpse's history)."""
        with self._lock:
            self._state = self.CLOSED
            self._fail_streak = 0
            self._slow_streak = 0

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "state": self._state,
                "opened_total": self.opened_total,
                "probes_total": self.probes_total,
                "fail_streak": self._fail_streak,
                "slow_streak": self._slow_streak,
                "failure_threshold": self.failure_threshold,
                "latency_threshold_ms": self.latency_threshold_ms,
            }
