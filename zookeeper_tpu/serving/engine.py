"""Bucketed, pre-compiled inference engine.

Serving traffic arrives at arbitrary batch sizes; compiling a fresh XLA
program per size would stall requests for seconds and fill the compile
cache with near-duplicates. The engine therefore quantizes every request
batch to a small set of *shape buckets* (``batch_buckets``, e.g.
``(1, 8, 32, 128)``), pads up to the bucket, runs the ONE compiled
program for that bucket, and slices the padding back off. Token models
additionally bucket the sequence axis (``seq_buckets``) — valid for
causal attention, where right-padding cannot influence earlier
positions.

Compilation discipline:

- Every bucket's forward is AOT-compiled (``jit(...).lower().compile()``)
  into an explicit cache keyed on ``(batch_bucket, seq_bucket, dtype,
  mesh)``; ``warmup()`` pre-compiles every configured bucket so the
  first real request never pays a compile, and ``compile_count`` lets
  tests assert that a warmed bucket triggers ZERO further compiles.
- The forward is *donation-safe*: the weights are passed (never
  donated — they serve every subsequent request, unlike the training
  step's consumed state), and the padded input is not donated either
  (no output aliases its shape, so donation would buy nothing and make
  XLA warn on every compile — the ``donate_slab`` lesson).
- Sharding comes from the same :class:`~zookeeper_tpu.parallel.\
partitioner.Partitioner` family training uses
  (``Partitioner.compile_forward``): the weights are placed once under
  the partitioner's rules (dp replication / tp / explicit FSDP rules)
  and the batch axis shards like a training batch, so a model trains
  and serves under one layout.

Per-row exactness: padding rows are zeros and inference is row-
independent (BatchNorm uses running stats, attention is causal), so a
request's rows are bit-identical whichever bucket they ride in — the
invariant the MicroBatcher's coalescing correctness rests on (pinned in
tests/serving/).

Checkpoint→serving streaming: ``swap_weights`` replaces the bound
weights IN PLACE without touching the compile cache (the executables
take the variables as an argument — same bucket shapes ⇒ same
programs), and ``watch_checkpoints`` polls a live training run's
``Checkpointer`` directory for newly FINALIZED steps and hot-swaps
them in, turning train→export→serve into train→serve-continuously
(docs/DESIGN.md §12). The swap is one Python reference assignment and
``infer`` reads the reference exactly once per dispatch, so every
micro-batch is served entirely by one weight version — atomic w.r.t.
in-flight ``MicroBatcher`` dispatches by construction.
"""

import logging
import threading
import time
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.observability import trace as _trace

logger = logging.getLogger(__name__)

Array = Any


def _tree_has_packed_kernels(tree: Any) -> bool:
    """Walk a (possibly frozen) params mapping for ``kernel_packed``
    leaves — the marker the packed layers store their bit-packed conv/
    dense kernels under (ops/layers.py). Structural, not numeric: any
    packed layer makes the deployment a packed one."""
    items = getattr(tree, "items", None)
    if items is None:
        return False
    for key, value in items():
        if key == "kernel_packed" or _tree_has_packed_kernels(value):
            return True
    return False


@component
class InferenceEngine:
    """Compiled, bucketed forward passes over a bound model.

    Configure the buckets as Fields; bind the runtime objects (apply_fn,
    weights, input spec, partitioner) with :meth:`bind` — they are not
    CLI-expressible. ``infer(x)`` serves one already-assembled batch of
    at most ``max_batch`` rows; request coalescing/splitting lives in
    :class:`~zookeeper_tpu.serving.batcher.MicroBatcher`.
    """

    #: Padded batch sizes, ascending. Each distinct bucket costs one
    #: compile (at ``warmup()``) and its activation HBM; more buckets =
    #: less padding waste per dispatch. The largest bucket is the
    #: engine's max dispatch size (the batcher splits bigger requests).
    batch_buckets: Sequence[int] = Field((1, 8, 32, 128))
    #: Sequence-length buckets for token inputs (empty = no sequence
    #: padding). Right-padding is only output-preserving under CAUSAL
    #: attention; non-causal models must serve at exact lengths.
    seq_buckets: Sequence[int] = Field(())

    # -- runtime binding -------------------------------------------------

    def bind(
        self,
        apply_fn: Callable[..., Array],
        params: Any,
        model_state: Any,
        input_shape: Sequence[int],
        *,
        dtype: Any = None,
        partitioner: Any = None,
    ) -> "InferenceEngine":
        """Attach the model to serve.

        ``apply_fn`` follows the repo's module convention
        (``apply(variables, x, training=False)``); ``input_shape`` is the
        per-example shape (no batch dim); ``dtype`` the input dtype
        (defaults to float32; token models pass int32). ``partitioner``
        defaults to a fresh single-device one; pass the training
        partitioner to serve under the training dp/tp layout.
        """
        import jax

        buckets = tuple(int(b) for b in self.batch_buckets)
        if not buckets or any(b < 1 for b in buckets) or list(buckets) != sorted(
            set(buckets)
        ):
            raise ValueError(
                f"batch_buckets={self.batch_buckets!r} must be a non-empty, "
                "strictly-ascending tuple of positive sizes."
            )
        seq_buckets = tuple(int(s) for s in self.seq_buckets)
        if seq_buckets and list(seq_buckets) != sorted(set(seq_buckets)):
            raise ValueError(
                f"seq_buckets={self.seq_buckets!r} must be "
                "strictly ascending."
            )
        if partitioner is None:
            from zookeeper_tpu.parallel.partitioner import (
                SingleDevicePartitioner,
            )

            partitioner = SingleDevicePartitioner()
        partitioner.setup()
        object.__setattr__(self, "_apply_fn", apply_fn)
        object.__setattr__(self, "_partitioner", partitioner)
        object.__setattr__(
            self,
            "_variables",
            self._place_variables({"params": params, **dict(model_state or {})}),
        )
        object.__setattr__(self, "_input_shape", tuple(input_shape))
        object.__setattr__(
            self, "_dtype", np.dtype(dtype) if dtype is not None else np.float32
        )
        object.__setattr__(self, "_cache", {})
        object.__setattr__(self, "_compile_count", 0)
        # Recompile-watchdog state (docs/DESIGN.md §14): a rebind is a
        # fresh program family — "warmed" starts over.
        object.__setattr__(self, "_warmed", False)
        object.__setattr__(self, "_recompiles_detected", 0)
        object.__setattr__(self, "_flops_by_key", {})
        object.__setattr__(self, "_last_dispatch_flops", None)
        # Packed-deployment detection (docs/DESIGN.md §21): a params tree
        # carrying bit-packed kernels serves binary compute, so its
        # dispatches are additionally rated against the measured int8
        # roofline (zk_serve_mfu_int8).
        object.__setattr__(
            self, "_packed_deployment", _tree_has_packed_kernels(params)
        )
        return self

    @property
    def packed_deployment(self) -> bool:
        """True when the bound params tree carries bit-packed kernels
        (``kernel_packed`` leaves) — the §21 binary deployment path."""
        return bool(getattr(self, "_packed_deployment", False))

    def _place_variables(self, variables: Any) -> Any:
        """Device placement under the bound partitioner's rules — the
        ONE placement path shared by ``bind`` and ``swap_weights`` so a
        hot-swapped weight set lands under exactly the layout the
        cached executables were compiled for."""
        import jax

        sharding = self._partitioner.variables_sharding(variables)
        if sharding is not None:
            return jax.tree.map(jax.device_put, variables, sharding)
        return jax.device_put(variables)

    def swap_weights(self, params: Any, model_state: Any = None) -> None:
        """Atomically replace the served weights WITHOUT recompiling.

        The new tree must match the bound one in structure, leaf shapes,
        and dtypes — the cached executables were compiled against those
        (same shapes ⇒ same programs; anything else must fail loudly
        here, not as an XLA argument error mid-request). The swap itself
        is one reference assignment and ``infer`` reads the reference
        exactly once per dispatch, so every in-flight micro-batch is
        served entirely by the version it started with.
        """
        import jax

        self._require_bound()
        new = {"params": params, **dict(model_state or {})}
        cur = self._variables
        want_s, got_s = jax.tree.structure(cur), jax.tree.structure(new)
        if want_s != got_s:
            raise ValueError(
                "swap_weights: new variables tree does not match the "
                f"bound structure (bound {want_s}, got {got_s}); the "
                "compiled buckets serve ONE architecture."
            )
        bad = [
            f"{np.shape(g)}/{np.dtype(getattr(g, 'dtype', type(g)))} where "
            f"the engine serves {np.shape(w)}/{np.dtype(w.dtype)}"
            for w, g in zip(jax.tree.leaves(cur), jax.tree.leaves(new))
            if tuple(np.shape(g)) != tuple(np.shape(w))
            or np.dtype(getattr(g, "dtype", np.float32)) != np.dtype(w.dtype)
        ]
        if bad:
            raise ValueError(
                "swap_weights: leaf shape/dtype mismatch — "
                + "; ".join(bad[:4])
                + (" ..." if len(bad) > 4 else "")
                + ". The cached executables were compiled for the bound "
                "shapes; a differently-sized checkpoint needs a fresh "
                "bind()."
            )
        with _trace.span("weight_swap"):
            placed = self._place_variables(new)
            # Atomic w.r.t. dispatches: infer() snapshots this reference
            # once per call.
            object.__setattr__(self, "_variables", placed)

    def watch_checkpoints(
        self,
        directory: str,
        *,
        weights: str = "ema",
        poll_interval_s: float = 2.0,
        metrics: Any = None,
        start: bool = True,
        initial_step: Optional[int] = None,
    ) -> "CheckpointWatcher":
        """Serve a LIVE training run: poll ``directory`` (a
        ``Checkpointer`` tree) for newly finalized steps and hot-swap
        each one in via :meth:`swap_weights` — no recompiles, no
        restarts, each request served entirely by one weight version.
        ``weights`` picks EMA vs raw exactly like the cold loaders
        ("ema" is the ship-weights default for a run with ``ema_decay``
        on; use "auto"/"raw" otherwise). ``start=False`` returns the
        watcher unstarted for deterministic single-step polling
        (``poll_once``) — the tier-1 test mode. ``metrics`` is an
        optional :class:`~zookeeper_tpu.serving.metrics.ServingMetrics`
        recording ``weight_swaps`` / ``weight_swap_ms`` /
        ``serving_weights_step``. ``initial_step`` marks that step as
        already live (the caller just bound its weights — e.g.
        ``ServingConfig.build_service``), so the watcher does not
        redundantly reload and re-swap it at startup.

        With ``start=True`` the FIRST poll runs eagerly on the calling
        thread: a configuration bug (``weights="ema"`` against an
        EMA-less run, a structure mismatch) raises HERE, at the call
        site, instead of silently killing the daemon thread."""
        import os

        self._require_bound()
        if not os.path.isdir(os.path.expanduser(directory)):
            # Not an error — serving may legitimately start before the
            # training run's first save creates the directory — but a
            # TYPO'd path would otherwise poll nothing forever with
            # healthy-looking metrics. Name it loudly, once.
            logger.warning(
                "watch_checkpoints: %r does not exist (yet); polling "
                "continues — if this path is misspelled, no checkpoint "
                "will ever stream in",
                directory,
            )
        watcher = CheckpointWatcher(
            self,
            directory,
            weights=weights,
            poll_interval_s=poll_interval_s,
            metrics=metrics,
            initial_step=initial_step,
        )
        if start:
            watcher.poll_once()  # config errors surface synchronously
            watcher.start()
        return watcher

    def _require_bound(self) -> None:
        if getattr(self, "_apply_fn", None) is None:
            raise RuntimeError(
                "InferenceEngine is not bound: call "
                "engine.bind(apply_fn, params, model_state, input_shape) "
                "before warmup()/infer()."
            )

    # -- bucket arithmetic ----------------------------------------------

    @property
    def max_batch(self) -> int:
        return max(int(b) for b in self.batch_buckets)

    @property
    def compile_count(self) -> int:
        """Number of XLA compiles performed so far (cache misses). After
        ``warmup()`` this is exactly ``len(batch_buckets) * max(1,
        len(seq_buckets))`` and serving warmed buckets must not move it."""
        return getattr(self, "_compile_count", 0)

    def bucket_for(self, n: int) -> int:
        """Smallest batch bucket holding ``n`` rows."""
        if n < 1:
            raise ValueError(f"batch of {n} rows is not servable.")
        for b in self.batch_buckets:
            if int(b) >= n:
                return int(b)
        raise ValueError(
            f"batch of {n} rows exceeds the largest bucket "
            f"{self.max_batch}; split it (MicroBatcher does this "
            "automatically) or widen batch_buckets."
        )

    def _seq_bucket_for(self, seq: int) -> int:
        for s in self.seq_buckets:
            if int(s) >= seq:
                return int(s)
        raise ValueError(
            f"sequence length {seq} exceeds the largest seq bucket "
            f"{max(int(s) for s in self.seq_buckets)}; widen seq_buckets."
        )

    # -- compile cache ---------------------------------------------------

    def _bucket_shape(
        self, bucket: int, seq_bucket: Optional[int]
    ) -> Tuple[int, ...]:
        shape = (bucket, *self._input_shape)
        if seq_bucket is not None:
            shape = (bucket, seq_bucket, *self._input_shape[1:])
        return shape

    def _compiled(
        self,
        bucket: int,
        seq_bucket: Optional[int],
        dtype,
        *,
        during_dispatch: bool = False,
    ):
        """The AOT-compiled forward for one shape bucket, plus whether
        the OUTPUT carries the sequence axis (cache-keyed on bucket,
        dtype, and the partitioner's mesh — a rebound mesh must never
        serve another mesh's executable).

        ``during_dispatch`` marks a compile triggered by ``infer``
        rather than ``warmup()``: once the engine has been warmed, any
        such compile is a serving stall that the bucket ladder was
        supposed to prevent — it emits a ``recompile_detected`` trace
        event and bumps ``zk_serving_recompiles_total`` so a recompile
        eating tail latency is self-announcing instead of forensic
        (the ``compile_count`` delta was only visible to tests)."""
        import jax

        self._require_bound()
        key = (bucket, seq_bucket, str(np.dtype(dtype)), self._partitioner.mesh)
        cached = self._cache.get(key)
        if cached is not None:
            return cached
        if during_dispatch and getattr(self, "_warmed", False):
            from zookeeper_tpu.observability.registry import default_registry

            object.__setattr__(
                self,
                "_recompiles_detected",
                getattr(self, "_recompiles_detected", 0) + 1,
            )
            default_registry().counter(
                "zk_serving_recompiles_total",
                help="post-warmup compiles triggered on the request "
                "path (each one is a serving stall)",
            ).inc()
            _trace.event(
                "recompile_detected",
                attrs={
                    "bucket": bucket,
                    "seq_bucket": seq_bucket,
                    "dtype": str(np.dtype(dtype)),
                },
            )
            # Flight-recorder trigger (docs/DESIGN.md §16): a recompile
            # mid-traffic is exactly the stall whose evidence (which
            # requests waited, what shapes arrived) evicts fast.
            from zookeeper_tpu.observability import recorder as _recorder

            _recorder.notify(
                "recompile_detected",
                attrs={
                    "bucket": bucket,
                    "seq_bucket": seq_bucket,
                    "dtype": str(np.dtype(dtype)),
                },
            )
            logger.warning(
                "post-warmup recompile on the request path "
                "(bucket=%d, seq=%s, dtype=%s): requests are stalling "
                "on XLA — widen/rewarm the bucket ladder",
                bucket,
                seq_bucket,
                np.dtype(dtype),
            )
        apply_fn = self._apply_fn

        def forward(variables, x):
            return apply_fn(variables, x, training=False)

        out_tracks_seq = False
        if seq_bucket is not None:
            # Does output axis 1 follow the sequence axis? Decided by
            # abstract trace at two sequence lengths — a dimension-size
            # coincidence (e.g. a pooled [batch, classes] head whose
            # class count equals the seq bucket) must NOT get its
            # classes sliced off as "padding".
            def out_shape(s):
                return jax.eval_shape(
                    forward,
                    self._variables,
                    jax.ShapeDtypeStruct(
                        self._bucket_shape(bucket, s), np.dtype(dtype)
                    ),
                ).shape

            a = out_shape(seq_bucket)
            b = out_shape(max(1, seq_bucket - 1))
            out_tracks_seq = (
                len(a) >= 2 and len(b) >= 2 and a[1] != b[1]
            )
        jitted = self._partitioner.compile_forward(
            forward, self._variables, batch_rows=bucket
        )
        dummy = jax.ShapeDtypeStruct(
            self._bucket_shape(bucket, seq_bucket), np.dtype(dtype)
        )
        t0 = time.perf_counter()
        lowered = jitted.lower(self._variables, dummy)
        t1 = time.perf_counter()
        executable = lowered.compile()
        t2 = time.perf_counter()
        compiled = (executable, out_tracks_seq)
        self._cache[key] = compiled
        object.__setattr__(self, "_compile_count", self._compile_count + 1)
        # Ledger row (docs/DESIGN.md §14): this bucket's identity,
        # FLOPs/bytes, compile wall time, and memory analysis — the
        # per-program accounting behind zk_serve_mfu and /statusz.
        from zookeeper_tpu.observability.ledger import default_ledger

        record = default_ledger().record(
            "serve_forward",
            f"{type(self._partitioner).__name__}/b{bucket}"
            + (f"s{seq_bucket}" if seq_bucket is not None else "")
            + f"/{np.dtype(dtype)}",
            lowered=lowered,
            compiled=executable,
            lower_ms=(t1 - t0) * 1e3,
            compile_ms=(t2 - t1) * 1e3,
            attrs={
                "bucket": bucket,
                "seq_bucket": seq_bucket,
                "during_dispatch": bool(during_dispatch),
            },
        )
        self._flops_by_key[key] = record.flops
        return compiled

    def warmup(self) -> int:
        """Pre-compile every configured (batch, seq) bucket so no request
        ever waits on XLA. Returns the number of cached executables."""
        self._require_bound()
        seqs = tuple(int(s) for s in self.seq_buckets) or (None,)
        for bucket in self.batch_buckets:
            for seq in seqs:
                self._compiled(int(bucket), seq, self._dtype)
        # From here on, a request-path compile is a detected recompile.
        object.__setattr__(self, "_warmed", True)
        return len(self._cache)

    @property
    def recompiles_detected(self) -> int:
        """Post-warmup compiles triggered on the request path (each
        one stalled requests on XLA); mirrored to the
        ``zk_serving_recompiles_total`` counter and a
        ``recompile_detected`` trace event as they happen."""
        return getattr(self, "_recompiles_detected", 0)

    def observe_dispatch(self, rows: int, seconds: float) -> None:
        """Record one completed (readback-bounded) dispatch: feed the
        serve-dispatch watchdog and publish ``zk_serve_mfu`` /
        ``zk_serve_dispatch_ms``. Called by the MicroBatcher after its
        ``device_get`` — the only place dispatch wall time is honest
        (``infer`` returns an un-synced device array). The FLOPs are
        the LAST dispatched bucket's ledger row; with the batcher's
        single dispatch path the pairing is exact. ``rows`` (occupied,
        pre-padding) renders as ``zk_serve_dispatch_rows`` — it does
        NOT scale the MFU: the device executes the padded bucket, so
        bucket FLOPs over wall time IS hardware utilization, and the
        rows gauge is how far from it request goodput sits."""
        from zookeeper_tpu.observability import ledger as _ledger
        from zookeeper_tpu.observability.registry import default_registry

        if seconds <= 0:
            return
        dog = getattr(self, "_dispatch_watchdog", None)
        if dog is None:
            from zookeeper_tpu.observability.watchdog import StepTimeWatchdog

            # Same 5ms-excess false-positive floor as training; see
            # docs/DESIGN.md §14.
            dog = StepTimeWatchdog("serve_dispatch", min_excess_s=0.005)
            object.__setattr__(self, "_dispatch_watchdog", dog)
        dog.observe(seconds)
        reg = default_registry()
        reg.gauge(
            "zk_serve_dispatch_ms",
            help="last coalesced dispatch wall time (readback-bounded)",
        ).set(seconds * 1e3)
        reg.gauge(
            "zk_serve_dispatch_rows",
            help="occupied (pre-padding) rows of the last coalesced "
            "dispatch — goodput context for the padded-bucket MFU",
        ).set(max(0, int(rows)))
        flops = getattr(self, "_last_dispatch_flops", None)
        peak = getattr(self, "_mfu_peak", None)
        if peak is None:
            from zookeeper_tpu.observability.peaks import reference_peak_flops

            peak = reference_peak_flops()[0]
            object.__setattr__(self, "_mfu_peak", peak)
        value = _ledger.mfu(flops, seconds, peak)
        reg.gauge(
            "zk_serve_mfu",
            help="last dispatch: ledger FLOPs / wall time / reference "
            "bf16 peak (-1 = cost analysis unavailable)",
            initial=-1,
        ).set(value if value is not None else -1)
        # §21 companion gauge: packed (binary) deployments are rated
        # against the measured int8 roofline — the honest peak for a
        # compute path whose promise is int-throughput, not bf16 FLOPs.
        # ALWAYS rendered (the scrape smoke asserts presence on every
        # service); real values only for packed deployments, -1 keeps
        # the §14 mfu() totality contract everywhere else.
        peak8 = getattr(self, "_mfu_peak_int8", None)
        if peak8 is None:
            from zookeeper_tpu.observability.peaks import (
                reference_int8_peak_flops,
            )

            peak8 = reference_int8_peak_flops()[0]
            object.__setattr__(self, "_mfu_peak_int8", peak8)
        value8 = (
            _ledger.mfu(flops, seconds, peak8)
            if self.packed_deployment
            else None
        )
        reg.gauge(
            "zk_serve_mfu_int8",
            help="last packed-deployment dispatch: ledger FLOPs / wall "
            "time / measured int8 peak (-1 = not a packed deployment or "
            "cost analysis unavailable)",
            initial=-1,
        ).set(value8 if value8 is not None else -1)

    # -- serving ---------------------------------------------------------

    def infer(self, x: Array) -> Array:
        """Forward one batch ``[n, *input_shape]`` (n <= ``max_batch``):
        pad to the bucket, dispatch the compiled program, slice the
        padding back off. Returns a device array ``[n, ...]`` — the
        caller decides when to pay the host readback (the batcher does
        one ``device_get`` per coalesced dispatch, not per request)."""
        x = np.asarray(x)
        self._require_bound()
        # ONE read of the weights reference per dispatch: a concurrent
        # swap_weights lands either entirely before or entirely after
        # this batch (the hot-swap atomicity contract).
        variables = self._variables
        n = x.shape[0]
        bucket = self.bucket_for(n)
        seq_bucket = None
        orig_seq = None
        if self.seq_buckets:
            if x.ndim < 2:
                raise ValueError(
                    "seq_buckets configured but the input has no sequence "
                    f"axis (shape {x.shape})."
                )
            orig_seq = x.shape[1]
            seq_bucket = self._seq_bucket_for(orig_seq)
        pad = [(0, bucket - n)] + [(0, 0)] * (x.ndim - 1)
        if seq_bucket is not None:
            pad[1] = (0, seq_bucket - orig_seq)
        if any(p != (0, 0) for p in pad):
            x = np.pad(x, pad)  # zero padding: row-independent forward
        x = x.astype(self._dtype, copy=False)
        compiled, out_tracks_seq = self._compiled(
            bucket, seq_bucket, x.dtype, during_dispatch=True
        )
        # The bucket this dispatch runs under, for observe_dispatch's
        # MFU pairing (single dispatch path: the batcher's readback
        # immediately follows this infer).
        object.__setattr__(
            self,
            "_last_dispatch_flops",
            self._flops_by_key.get(
                (bucket, seq_bucket, str(x.dtype), self._partitioner.mesh)
            ),
        )
        with _trace.span(
            "engine_infer",
            attrs=(
                {"rows": int(n), "bucket": bucket}
                if _trace.enabled()
                else None
            ),
        ):
            out = compiled(variables, x)[:n]
        if out_tracks_seq and orig_seq != seq_bucket:
            out = out[:, :orig_seq]
        return out

    def __call__(self, x: Array) -> Array:
        return self.infer(x)


class CheckpointWatcher:
    """Checkpoint→serving streaming: tail a training run's
    ``Checkpointer`` directory and hot-swap newly FINALIZED steps into
    a live :class:`InferenceEngine`.

    Discovery goes through
    :func:`~zookeeper_tpu.training.checkpoint.finalized_steps` — only
    atomically-finalized steps are ever visible, so a torn async write
    or a crash mid-save can never be served. A step that vanishes
    between discovery and load (retention GC racing the poll — the
    same race ``restore_state`` tolerates) is skipped with a warning
    and the next poll simply picks up the then-newest step.

    ``poll_once()`` is the deterministic unit (returns the swapped step
    or None); ``start()`` runs it on a daemon thread every
    ``poll_interval_s``. ``stop()`` is idempotent.

    Known cost: each swap's ``load_inference_model`` is a target-free
    restore of the FULL saved TrainState, optimizer state included
    (~2x params for Adam-family), which is immediately dropped — the
    installed orbax's ``StandardRestore`` rejects ``PLACEHOLDER``
    targets (see ``Checkpointer._restore_step``), so a partial read is
    not available; revisit when orbax grows per-leaf skipping. The IO
    runs on the watcher thread, never a request path.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        directory: str,
        *,
        weights: str = "ema",
        poll_interval_s: float = 2.0,
        metrics: Any = None,
        initial_step: Optional[int] = None,
    ) -> None:
        if weights not in ("auto", "ema", "raw"):
            raise ValueError(
                f"weights={weights!r} unknown; choose auto/ema/raw."
            )
        if poll_interval_s <= 0:
            raise ValueError(
                f"poll_interval_s={poll_interval_s} must be > 0."
            )
        self._engine = engine
        self._directory = directory
        self._weights = weights
        self._poll_interval_s = float(poll_interval_s)
        self._metrics = metrics
        # initial_step = the caller already serves this step's weights
        # (bound at load time): it is live without a swap, and only
        # NEWER steps trigger one.
        self._current_step: Optional[int] = (
            int(initial_step) if initial_step is not None else None
        )
        if initial_step is not None and metrics is not None:
            metrics.record_weights_step(int(initial_step))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._swaps = 0
        # poll_once is callable both from the daemon thread and
        # directly (tests, manual refresh): serialize the two.
        self._poll_lock = threading.Lock()

    @property
    def current_step(self) -> Optional[int]:
        """The training step whose weights are live (None until the
        first successful swap)."""
        return self._current_step

    @property
    def swaps(self) -> int:
        return self._swaps

    @property
    def alive(self) -> bool:
        """Whether the daemon poller is still following the directory
        (False after ``stop()`` OR after a fatal config error killed
        the loop — the check an operator/health probe should use before
        trusting ``serving_weights_step`` as 'live-following')."""
        thread = self._thread
        return (
            thread is not None
            and thread.is_alive()
            and not self._stop.is_set()
        )

    def poll_once(self) -> Optional[int]:
        """One poll: when a finalized step newer than ``current_step``
        exists, load it (EMA/raw per ``weights``) and swap it in.
        Returns the newly-live step, or None (nothing new, or the
        newest step vanished/failed to load — retried next poll)."""
        with self._poll_lock:
            return self._poll_once_locked()

    def _poll_once_locked(self) -> Optional[int]:
        from zookeeper_tpu.training.checkpoint import (
            CheckpointUnreadableError,
            finalized_steps,
            load_inference_model,
        )

        steps = finalized_steps(self._directory)
        if not steps:
            return None
        newest = steps[-1]
        if self._current_step is not None and newest <= self._current_step:
            return None
        t0 = time.perf_counter()
        try:
            params, model_state = load_inference_model(
                self._directory, weights=self._weights, step=newest
            )
        except CheckpointUnreadableError as e:
            # A finalized-but-torn step (post-crash disk state) or
            # files vanishing under the read: weather, exactly like
            # restore_state's walk — warn and retry next poll.
            logger.warning(
                "checkpoint watcher: step %d could not be loaded "
                "(%s); retrying at the next poll",
                newest,
                e,
            )
            return None
        except ValueError:
            # A CONFIGURATION bug (weights="ema" on an EMA-less run,
            # structure validation): silently retrying would pin
            # serving to stale weights while hiding it. Stop loudly.
            self._record_fatal_stop()
            raise
        except Exception as e:
            logger.warning(
                "checkpoint watcher: step %d could not be loaded (%s); "
                "retrying at the next poll",
                newest,
                e,
            )
            return None
        try:
            self._engine.swap_weights(params, model_state)
        except ValueError:
            # Shape/structure mismatch against the compiled buckets:
            # configuration bug, never weather. Stop loudly.
            self._record_fatal_stop()
            raise
        swap_ms = (time.perf_counter() - t0) * 1e3
        self._current_step = newest
        self._swaps += 1
        _trace.event(
            "ckpt_hot_swap",
            step=newest,
            attrs={"swap_ms": round(swap_ms, 3)},
        )
        if self._metrics is not None:
            self._metrics.record_weight_swap(swap_ms, newest)
        logger.info(
            "serving weights hot-swapped to training step %d (%.1f ms, "
            "no recompile)",
            newest,
            swap_ms,
        )
        return newest

    def _record_fatal_stop(self) -> None:
        """Kill the watcher over a configuration error. The metric
        lands BEFORE the stop flag flips ``alive``: anyone who observes
        the watcher dead must already see ``watcher_stopped`` counted —
        the staleness gauge must be distinguishable from "up to date"
        the moment it matters."""
        if self._metrics is not None:
            self._metrics.record_watcher_stopped()
        self._stop.set()

    def start(self) -> "CheckpointWatcher":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception as e:
                    logger.error(
                        "checkpoint watcher stopped: %s", e
                    )
                    # Fatal paths inside poll_once already counted
                    # watcher_stopped; anything else dies here and
                    # counts now, metric-before-flag for the same
                    # observability ordering.
                    if not self._stop.is_set():
                        self._record_fatal_stop()
                    return
                self._stop.wait(self._poll_interval_s)

        self._thread = threading.Thread(
            target=loop, name="zk-ckpt-watcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=10)
            self._thread = None
