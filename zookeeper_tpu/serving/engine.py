"""Bucketed, pre-compiled inference engine.

Serving traffic arrives at arbitrary batch sizes; compiling a fresh XLA
program per size would stall requests for seconds and fill the compile
cache with near-duplicates. The engine therefore quantizes every request
batch to a small set of *shape buckets* (``batch_buckets``, e.g.
``(1, 8, 32, 128)``), pads up to the bucket, runs the ONE compiled
program for that bucket, and slices the padding back off. Token models
additionally bucket the sequence axis (``seq_buckets``) — valid for
causal attention, where right-padding cannot influence earlier
positions.

Compilation discipline:

- Every bucket's forward is AOT-compiled (``jit(...).lower().compile()``)
  into an explicit cache keyed on ``(batch_bucket, seq_bucket, dtype,
  mesh)``; ``warmup()`` pre-compiles every configured bucket so the
  first real request never pays a compile, and ``compile_count`` lets
  tests assert that a warmed bucket triggers ZERO further compiles.
- The forward is *donation-safe*: the weights are passed (never
  donated — they serve every subsequent request, unlike the training
  step's consumed state), and the padded input is not donated either
  (no output aliases its shape, so donation would buy nothing and make
  XLA warn on every compile — the ``donate_slab`` lesson).
- Sharding comes from the same :class:`~zookeeper_tpu.parallel.\
partitioner.Partitioner` family training uses
  (``Partitioner.compile_forward``): the weights are placed once under
  the partitioner's rules (dp replication / tp / explicit FSDP rules)
  and the batch axis shards like a training batch, so a model trains
  and serves under one layout.

Per-row exactness: padding rows are zeros and inference is row-
independent (BatchNorm uses running stats, attention is causal), so a
request's rows are bit-identical whichever bucket they ride in — the
invariant the MicroBatcher's coalescing correctness rests on (pinned in
tests/serving/).
"""

from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from zookeeper_tpu.core import Field, component

Array = Any


@component
class InferenceEngine:
    """Compiled, bucketed forward passes over a bound model.

    Configure the buckets as Fields; bind the runtime objects (apply_fn,
    weights, input spec, partitioner) with :meth:`bind` — they are not
    CLI-expressible. ``infer(x)`` serves one already-assembled batch of
    at most ``max_batch`` rows; request coalescing/splitting lives in
    :class:`~zookeeper_tpu.serving.batcher.MicroBatcher`.
    """

    #: Padded batch sizes, ascending. Each distinct bucket costs one
    #: compile (at ``warmup()``) and its activation HBM; more buckets =
    #: less padding waste per dispatch. The largest bucket is the
    #: engine's max dispatch size (the batcher splits bigger requests).
    batch_buckets: Sequence[int] = Field((1, 8, 32, 128))
    #: Sequence-length buckets for token inputs (empty = no sequence
    #: padding). Right-padding is only output-preserving under CAUSAL
    #: attention; non-causal models must serve at exact lengths.
    seq_buckets: Sequence[int] = Field(())

    # -- runtime binding -------------------------------------------------

    def bind(
        self,
        apply_fn: Callable[..., Array],
        params: Any,
        model_state: Any,
        input_shape: Sequence[int],
        *,
        dtype: Any = None,
        partitioner: Any = None,
    ) -> "InferenceEngine":
        """Attach the model to serve.

        ``apply_fn`` follows the repo's module convention
        (``apply(variables, x, training=False)``); ``input_shape`` is the
        per-example shape (no batch dim); ``dtype`` the input dtype
        (defaults to float32; token models pass int32). ``partitioner``
        defaults to a fresh single-device one; pass the training
        partitioner to serve under the training dp/tp layout.
        """
        import jax

        buckets = tuple(int(b) for b in self.batch_buckets)
        if not buckets or any(b < 1 for b in buckets) or list(buckets) != sorted(
            set(buckets)
        ):
            raise ValueError(
                f"batch_buckets={self.batch_buckets!r} must be a non-empty, "
                "strictly-ascending tuple of positive sizes."
            )
        seq_buckets = tuple(int(s) for s in self.seq_buckets)
        if seq_buckets and list(seq_buckets) != sorted(set(seq_buckets)):
            raise ValueError(
                f"seq_buckets={self.seq_buckets!r} must be "
                "strictly ascending."
            )
        if partitioner is None:
            from zookeeper_tpu.parallel.partitioner import (
                SingleDevicePartitioner,
            )

            partitioner = SingleDevicePartitioner()
        partitioner.setup()
        variables = {"params": params, **dict(model_state or {})}
        sharding = partitioner.variables_sharding(variables)
        if sharding is not None:
            variables = jax.tree.map(jax.device_put, variables, sharding)
        else:
            variables = jax.device_put(variables)
        object.__setattr__(self, "_apply_fn", apply_fn)
        object.__setattr__(self, "_variables", variables)
        object.__setattr__(self, "_partitioner", partitioner)
        object.__setattr__(self, "_input_shape", tuple(input_shape))
        object.__setattr__(
            self, "_dtype", np.dtype(dtype) if dtype is not None else np.float32
        )
        object.__setattr__(self, "_cache", {})
        object.__setattr__(self, "_compile_count", 0)
        return self

    def _require_bound(self) -> None:
        if getattr(self, "_apply_fn", None) is None:
            raise RuntimeError(
                "InferenceEngine is not bound: call "
                "engine.bind(apply_fn, params, model_state, input_shape) "
                "before warmup()/infer()."
            )

    # -- bucket arithmetic ----------------------------------------------

    @property
    def max_batch(self) -> int:
        return max(int(b) for b in self.batch_buckets)

    @property
    def compile_count(self) -> int:
        """Number of XLA compiles performed so far (cache misses). After
        ``warmup()`` this is exactly ``len(batch_buckets) * max(1,
        len(seq_buckets))`` and serving warmed buckets must not move it."""
        return getattr(self, "_compile_count", 0)

    def bucket_for(self, n: int) -> int:
        """Smallest batch bucket holding ``n`` rows."""
        if n < 1:
            raise ValueError(f"batch of {n} rows is not servable.")
        for b in self.batch_buckets:
            if int(b) >= n:
                return int(b)
        raise ValueError(
            f"batch of {n} rows exceeds the largest bucket "
            f"{self.max_batch}; split it (MicroBatcher does this "
            "automatically) or widen batch_buckets."
        )

    def _seq_bucket_for(self, seq: int) -> int:
        for s in self.seq_buckets:
            if int(s) >= seq:
                return int(s)
        raise ValueError(
            f"sequence length {seq} exceeds the largest seq bucket "
            f"{max(int(s) for s in self.seq_buckets)}; widen seq_buckets."
        )

    # -- compile cache ---------------------------------------------------

    def _bucket_shape(
        self, bucket: int, seq_bucket: Optional[int]
    ) -> Tuple[int, ...]:
        shape = (bucket, *self._input_shape)
        if seq_bucket is not None:
            shape = (bucket, seq_bucket, *self._input_shape[1:])
        return shape

    def _compiled(self, bucket: int, seq_bucket: Optional[int], dtype):
        """The AOT-compiled forward for one shape bucket, plus whether
        the OUTPUT carries the sequence axis (cache-keyed on bucket,
        dtype, and the partitioner's mesh — a rebound mesh must never
        serve another mesh's executable)."""
        import jax

        self._require_bound()
        key = (bucket, seq_bucket, str(np.dtype(dtype)), self._partitioner.mesh)
        cached = self._cache.get(key)
        if cached is not None:
            return cached
        apply_fn = self._apply_fn

        def forward(variables, x):
            return apply_fn(variables, x, training=False)

        out_tracks_seq = False
        if seq_bucket is not None:
            # Does output axis 1 follow the sequence axis? Decided by
            # abstract trace at two sequence lengths — a dimension-size
            # coincidence (e.g. a pooled [batch, classes] head whose
            # class count equals the seq bucket) must NOT get its
            # classes sliced off as "padding".
            def out_shape(s):
                return jax.eval_shape(
                    forward,
                    self._variables,
                    jax.ShapeDtypeStruct(
                        self._bucket_shape(bucket, s), np.dtype(dtype)
                    ),
                ).shape

            a = out_shape(seq_bucket)
            b = out_shape(max(1, seq_bucket - 1))
            out_tracks_seq = (
                len(a) >= 2 and len(b) >= 2 and a[1] != b[1]
            )
        jitted = self._partitioner.compile_forward(
            forward, self._variables, batch_rows=bucket
        )
        dummy = jax.ShapeDtypeStruct(
            self._bucket_shape(bucket, seq_bucket), np.dtype(dtype)
        )
        compiled = (jitted.lower(self._variables, dummy).compile(),
                    out_tracks_seq)
        self._cache[key] = compiled
        object.__setattr__(self, "_compile_count", self._compile_count + 1)
        return compiled

    def warmup(self) -> int:
        """Pre-compile every configured (batch, seq) bucket so no request
        ever waits on XLA. Returns the number of cached executables."""
        self._require_bound()
        seqs = tuple(int(s) for s in self.seq_buckets) or (None,)
        for bucket in self.batch_buckets:
            for seq in seqs:
                self._compiled(int(bucket), seq, self._dtype)
        return len(self._cache)

    # -- serving ---------------------------------------------------------

    def infer(self, x: Array) -> Array:
        """Forward one batch ``[n, *input_shape]`` (n <= ``max_batch``):
        pad to the bucket, dispatch the compiled program, slice the
        padding back off. Returns a device array ``[n, ...]`` — the
        caller decides when to pay the host readback (the batcher does
        one ``device_get`` per coalesced dispatch, not per request)."""
        x = np.asarray(x)
        self._require_bound()
        n = x.shape[0]
        bucket = self.bucket_for(n)
        seq_bucket = None
        orig_seq = None
        if self.seq_buckets:
            if x.ndim < 2:
                raise ValueError(
                    "seq_buckets configured but the input has no sequence "
                    f"axis (shape {x.shape})."
                )
            orig_seq = x.shape[1]
            seq_bucket = self._seq_bucket_for(orig_seq)
        pad = [(0, bucket - n)] + [(0, 0)] * (x.ndim - 1)
        if seq_bucket is not None:
            pad[1] = (0, seq_bucket - orig_seq)
        if any(p != (0, 0) for p in pad):
            x = np.pad(x, pad)  # zero padding: row-independent forward
        x = x.astype(self._dtype, copy=False)
        compiled, out_tracks_seq = self._compiled(bucket, seq_bucket, x.dtype)
        out = compiled(self._variables, x)[:n]
        if out_tracks_seq and orig_seq != seq_bucket:
            out = out[:, :orig_seq]
        return out

    def __call__(self, x: Array) -> Array:
        return self.infer(x)
