"""KV page handoff between mesh slices (docs/DESIGN.md §22).

The unit of disaggregated serving is a completed prefill's device
state: the pool pages its prompt wrote. :class:`PageTransfer` moves
exactly those pages from the prefill engine's pool into freshly
adopted pages of the decode engine's pool:

1. **Export** (source, read-only): one compiled gather lifts the page
   ids into a contiguous ``transfer_width``-page block
   (``DecodeEngine.export_pages`` — the source pool is never donated;
   prefix-cache-shared pages may be mid-read by another lane).
2. **Move**: ``jax.device_put`` of the block onto the destination
   pool's shardings — a direct device-to-device copy when the runtime
   supports the route (same process, reachable slices). When it does
   not — or ``host_bounce=True`` forces the portable path — the block
   bounces through host memory under an explicit
   ``jax.transfer_guard("allow")`` scope, so a transfer-guarded
   process still fails LOUDLY on accidental device->host syncs
   elsewhere while this deliberate one stays legal.
3. **Import** (destination, donated): one compiled scatter lands the
   block at the adopted page ids (``DecodeEngine.import_pages`` —
   padding lanes carry the OOB sentinel and write nowhere).

Refcount custody is the CALLER's (the disagg scheduler): destination
pages are adopted BEFORE ``move`` and the source lane is released only
AFTER it returns — both pools hold ``leak_check() == 0`` at every
instant, including across an injected ``FaultPlan.fail_page_transfer``
(this module raises :class:`PageTransferError`; the scheduler unwinds
the adopted pages and fails only the victim stream).
"""

import time
from collections import deque
from typing import Optional, Sequence

import numpy as np

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.observability import trace as _trace

__all__ = ["PageTransfer", "PageTransferError"]


class PageTransferError(RuntimeError):
    """A page handoff failed (injected or real): the victim stream is
    failed cleanly, the destination pages are unwound, and BOTH pools
    stay leak-free — the scheduler's unwind contract."""


@component
class PageTransfer:
    """Mover of KV page blocks between two paged engines' pools (see
    module docstring). ``bind(src_engine, dst_engine)`` validates the
    geometry; ``move`` is the per-handoff call."""

    #: Force the portable host-bounce path even when a direct
    #: device-to-device put would work (A/B lever for the §22 transfer
    #: cost model; the direct path is attempted first by default).
    host_bounce: bool = Field(False)

    def bind(
        self, src_engine, dst_engine, metrics=None
    ) -> "PageTransfer":
        """Attach the two engines. Both must run the paged layout with
        the SAME transfer block geometry (page size and pages-per-block
        — one compiled shape serves every handoff in each direction)."""
        src_engine._require_bound()
        dst_engine._require_bound()
        if not src_engine.paged or not dst_engine.paged:
            raise ValueError(
                "page transfer needs kv_layout='paged' on BOTH roles; "
                f"got src={src_engine.kv_layout!r} "
                f"dst={dst_engine.kv_layout!r}."
            )
        if int(src_engine.page_size) != int(dst_engine.page_size):
            raise ValueError(
                f"page_size mismatch across roles: src="
                f"{src_engine.page_size} dst={dst_engine.page_size} — "
                "a transferred page would land misaligned."
            )
        if int(src_engine.transfer_width) != int(dst_engine.transfer_width):
            raise ValueError(
                f"transfer_width mismatch: src={src_engine.transfer_width}"
                f" dst={dst_engine.transfer_width} pages — align the "
                "roles' seq_buckets so one block shape serves both."
            )
        object.__setattr__(self, "_src", src_engine)
        object.__setattr__(self, "_dst", dst_engine)
        object.__setattr__(self, "_metrics", metrics)
        # Mutable accounting lives in containers (the component is
        # frozen): lifetime totals + a bounded latency window for the
        # p50 the result line / statusz report.
        object.__setattr__(
            self,
            "_stats",
            {"handoffs": 0, "pages": 0, "bytes": 0, "bounces": 0},
        )
        object.__setattr__(self, "_ms_window", deque(maxlen=512))
        return self

    def _require_bound(self) -> None:
        if getattr(self, "_src", None) is None:
            raise RuntimeError(
                "PageTransfer is not bound: call transfer.bind("
                "prefill_engine, decode_engine) first."
            )

    # -- the handoff -----------------------------------------------------

    def move(
        self,
        src_page_ids: Sequence[int],
        dst_page_ids: Sequence[int],
        rid: Optional[int] = None,
    ) -> float:
        """Move ``src_page_ids``'s pages into ``dst_page_ids`` (equal
        lengths; the destination ids come from
        ``PagePool.adopt_slot``). Returns the wall milliseconds.
        Raises :class:`PageTransferError` on an injected
        ``FaultPlan.fail_page_transfer`` BEFORE touching either device
        — the deterministic chaos seam."""
        from zookeeper_tpu.resilience import faults

        self._require_bound()
        if len(src_page_ids) != len(dst_page_ids):
            raise ValueError(
                f"page id lists must pair up: {len(src_page_ids)} src "
                f"vs {len(dst_page_ids)} dst."
            )
        plan = faults.active()
        if plan is not None and plan.take_fail_page_transfer():
            raise PageTransferError(
                "injected page-transfer failure "
                "(FaultPlan.fail_page_transfer): the handoff block "
                "never reached the decode pool."
            )
        n = len(src_page_ids)
        t0 = time.perf_counter()
        with _trace.span(
            "page_transfer",
            rid=rid,
            attrs={"pages": n} if _trace.enabled() else None,
        ):
            block = self._src.export_pages(src_page_ids)
            moved = self._place(block)
            self._dst.import_pages(moved, dst_page_ids)
        dt_ms = (time.perf_counter() - t0) * 1e3
        nbytes = self._block_bytes(block, n)
        stats = self._stats
        stats["handoffs"] += 1
        stats["pages"] += n
        stats["bytes"] += nbytes
        self._ms_window.append(dt_ms)
        if self._metrics is not None:
            self._metrics.record_transfer(n, nbytes, dt_ms)
        return dt_ms

    def _place(self, block):
        """Land the block on the destination pool's devices: direct
        device-to-device put when the runtime can route it, else the
        transfer-guarded host bounce. Sharding comes from the LIVE
        destination pool leaves — NamedSharding is shape-agnostic along
        the (replicated) pages axis, so the pool's own placement
        applies to the W-page block verbatim."""
        import jax

        dst_shardings = jax.tree.map(
            lambda leaf: leaf.sharding, self._dst._cache
        )
        if not self.host_bounce:
            try:
                return jax.tree.map(
                    lambda leaf, sh: jax.device_put(leaf, sh),
                    block,
                    dst_shardings,
                )
            except (
                ValueError,
                RuntimeError,
                NotImplementedError,
            ):
                # Route unavailable (e.g. a backend without direct
                # cross-slice puts): fall through to the bounce.
                pass
        self._stats["bounces"] += 1
        host = jax.tree.map(np.asarray, block)
        with jax.transfer_guard("allow"):
            return jax.tree.map(
                lambda leaf, sh: jax.device_put(leaf, sh),
                host,
                dst_shardings,
            )

    @staticmethod
    def _block_bytes(block, n_pages: int) -> int:
        """Real payload bytes of a handoff: every leaf's per-page bytes
        x the REAL page count (padding lanes carry garbage the import
        drops — they ride the wire but are not payload)."""
        import jax

        total = 0
        for leaf in jax.tree.leaves(block):
            w = int(np.shape(leaf)[0])
            total += (leaf.nbytes // max(1, w)) * n_pages
        return int(total)

    # -- accounting ------------------------------------------------------

    @property
    def handoffs(self) -> int:
        return self._stats["handoffs"] if hasattr(self, "_stats") else 0

    def transfer_ms_p50(self) -> float:
        """Median handoff wall time over the recent window (-1 before
        any handoff)."""
        window = getattr(self, "_ms_window", None)
        if not window:
            return -1.0
        return float(np.percentile(np.asarray(window), 50))

    def status(self) -> dict:
        """The ``/statusz`` ``transfer`` section."""
        self._require_bound()
        stats = self._stats
        return {
            "handoffs_total": int(stats["handoffs"]),
            "pages_total": int(stats["pages"]),
            "bytes_total": int(stats["bytes"]),
            "host_bounces": int(stats["bounces"]),
            "host_bounce_forced": bool(self.host_bounce),
            "transfer_width": int(self._src.transfer_width),
            "transfer_ms_p50": round(self.transfer_ms_p50(), 4),
        }
