"""Disaggregated prefill/decode serving (docs/DESIGN.md §22).

The decode subsystem's two programs have opposite resource shapes —
prefill is compute-bound and batches wide, the decode step is
memory-bound and latency-critical — so co-locating them makes each
other's tail: a long prefill stalls every active stream's next token.
This package splits them across MESH SLICES of one host, with the KV
page as the handoff unit:

- :class:`DisaggPartitioner` — role-aware topology: prefill and decode
  :class:`~zookeeper_tpu.parallel.partitioner.MeshPartitioner` slices
  over disjoint device lists (overlapping single-host fallback for the
  1-device CPU case).
- :class:`PageTransfer` — moves a completed prefill's pool pages into
  the decode pool: compiled gather -> ``jax.device_put`` onto the
  destination shardings (transfer-guarded host bounce as the portable
  fallback) -> compiled OOB-drop scatter. ``zk_transfer_*`` metrics.
- :class:`DisaggScheduler` — the split PrefillQueue/DecodeQueue loop
  over the inherited :class:`~zookeeper_tpu.serving.decode.scheduler.
  DecodeScheduler` machinery: admit into prefill lanes, deliver the
  first token (TTFT) at prefill, park until a decode slot frees, adopt
  + transfer + continue through the unchanged decode loop. Atomic
  refcount handoff — both pools ``leak_check() == 0`` at every
  instant, chaos-pinned.
- :class:`DisaggServingConfig` — the config citizen: one checkpoint,
  two role engines, ``examples/serve_lm.py --disagg``.

Greedy disagg output is certified token-identical to the single-mesh
``DecodeEngine`` — through slot refill, paged + int8 KV, and
speculative decoding (tests/serving/test_disagg.py).
"""

from zookeeper_tpu.serving.disagg.partition import DisaggPartitioner
from zookeeper_tpu.serving.disagg.scheduler import DisaggScheduler
from zookeeper_tpu.serving.disagg.service import DisaggServingConfig
from zookeeper_tpu.serving.disagg.transfer import (
    PageTransfer,
    PageTransferError,
)

__all__ = [
    "DisaggPartitioner",
    "DisaggScheduler",
    "DisaggServingConfig",
    "PageTransfer",
    "PageTransferError",
]
