"""The split prefill/decode scheduler (docs/DESIGN.md §22).

One :class:`~zookeeper_tpu.serving.decode.scheduler.DecodeScheduler`
loop, two engines. The inherited machinery — submit/shed/backpressure,
deadlines, rid minting, crash recovery, weight hot-swap staging —
carries over VERBATIM; only admission is re-expressed as two queues:

- **PrefillQueue** (the inherited ``_queue`` plus the prefill role's
  lane array): queued prompts ride bucketed prefill dispatches on the
  PREFILL engine, batched as wide as its ``prefill_buckets`` allow.
  The first token is delivered at prefill completion — TTFT is stamped
  HERE, so the handoff cost lands on token 2's inter-token gap, which
  is the disaggregation trade (wide prefill batching without decode
  jitter). A stream finished by its first token (EOS, ``max_new=1``,
  capacity) releases its lane and never transfers.
- **DecodeQueue** (the ``_parked`` deque of completed prefills): when
  a decode slot frees, the oldest handoff adopts destination pages
  (``PagePool.adopt_slot``), the :class:`~zookeeper_tpu.serving.disagg
  .transfer.PageTransfer` moves the prefill lane's pages across, and
  the stream continues through the UNCHANGED inherited decode loop —
  plain or speculative.

Refcount custody across the seam is atomic: destination pages are
adopted before the move, the source lane is released only after the
import lands, and every failure path (injected transfer failure,
prefill-role crash, close, deadline) unwinds whichever side it holds —
``leak_check() == 0`` on BOTH pools at every instant, pinned by the
chaos suite.

Chaos knobs (``resilience.faults``): ``fail_page_transfer`` fails the
next handoff's move (victim fails with
:class:`~zookeeper_tpu.serving.disagg.transfer.PageTransferError`,
everyone else unaffected); ``prefill_role_crash_at=N`` kills the
PREFILL role at the Nth handoff — its pool and lanes are lost
wholesale (reset, zero leaks by construction), every stream still on
the prefill side fails cleanly with partials readable, and the decode
role keeps serving its active slots.
"""

import logging
import time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from zookeeper_tpu.core import component
from zookeeper_tpu.observability import recorder as _recorder
from zookeeper_tpu.observability import trace as _trace
from zookeeper_tpu.observability.requests import RequestLog
from zookeeper_tpu.serving.batcher import RejectedError, WorkerCrashedError
from zookeeper_tpu.serving.decode.scheduler import (
    DecodeScheduler,
    DecodeStream,
)
from zookeeper_tpu.serving.disagg.transfer import (
    PageTransfer,
    PageTransferError,
)

logger = logging.getLogger(__name__)

__all__ = ["DisaggScheduler"]

#: A parked handoff: (stream, prefill lane, first token, prompt tokens).
_Handoff = Tuple[DecodeStream, int, int, int]


@component
class DisaggScheduler(DecodeScheduler):
    """Disaggregated continuous batching over a prefill engine and a
    decode engine joined by a :class:`PageTransfer` (see module
    docstring). All :class:`DecodeScheduler` fields apply unchanged."""

    # -- wiring ----------------------------------------------------------

    def bind(
        self,
        prefill_engine,
        decode_engine,
        transfer: PageTransfer,
        metrics=None,
        request_log=None,
        speculative=None,
    ) -> "DisaggScheduler":
        """Bind both roles. The DECODE engine is the inherited
        ``_engine`` (slots, decode loop, capacity contracts inherit);
        the prefill engine contributes lanes and the wide prefill
        grid; ``transfer`` must be bound to exactly this (prefill,
        decode) pair."""
        prefill_engine._require_bound()
        decode_engine._require_bound()
        if not prefill_engine.paged or not decode_engine.paged:
            raise ValueError(
                "disaggregated serving needs kv_layout='paged' on BOTH "
                "roles — the handoff unit is the page."
            )
        transfer._require_bound()
        if (
            transfer._src is not prefill_engine
            or transfer._dst is not decode_engine
        ):
            raise ValueError(
                "transfer is bound to a different engine pair; bind it "
                "as transfer.bind(prefill_engine, decode_engine)."
            )
        if prefill_engine.max_prompt < decode_engine.max_prompt:
            raise ValueError(
                f"prefill seq buckets top out at "
                f"{prefill_engine.max_prompt} tokens but the decode "
                f"role admits prompts up to {decode_engine.max_prompt} "
                "— widen the prefill engine's seq_buckets."
            )
        super().bind(
            decode_engine,
            metrics=metrics,
            request_log=(
                request_log
                if request_log is not None
                else RequestLog("disagg")
            ),
            speculative=speculative,
        )
        object.__setattr__(self, "_prefill_engine", prefill_engine)
        object.__setattr__(self, "_transfer", transfer)
        lanes = int(prefill_engine.slots)
        object.__setattr__(self, "_lane_stream", [None] * lanes)
        object.__setattr__(self, "_parked", deque())
        return self

    @property
    def prefill_engine(self):
        return getattr(self, "_prefill_engine", None)

    @property
    def transfer(self) -> Optional[PageTransfer]:
        return getattr(self, "_transfer", None)

    @property
    def parked(self) -> int:
        with self._lock:
            return len(self._parked)

    def _free_lane(self, lane: int) -> None:
        """Every lane retirement funnels here (the decode side's
        ``_free_slot`` twin): pages released, lane reusable. Caller
        holds ``_lock``."""
        self._lane_stream[lane] = None
        self._prefill_engine.release_slot(lane)

    # -- the split admission ---------------------------------------------

    def _admit(self) -> None:
        """One admission round: land parked handoffs first (frees
        lanes), refill prefill lanes from the queue, then land any
        handoff the fresh prefill round just parked — a single stream
        on an idle service reaches its decode slot within ONE scheduler
        iteration."""
        self._admit_decode()
        self._admit_prefill()
        self._admit_decode()

    def _admit_prefill(self) -> None:
        """PrefillQueue step: the base ``_admit`` re-expressed on the
        PREFILL engine's lanes. Identical discipline — reserve under
        ``_lock``, page-plan under ``_lock``, dispatch outside,
        identity-checked commit — but completion PARKS the stream as a
        handoff instead of entering the decode loop."""
        engine = self._prefill_engine
        while True:
            with self._lock:
                if self._swap_pending is not None or not self._queue:
                    return
                free = [
                    i for i, s in enumerate(self._lane_stream) if s is None
                ]
                if not free:
                    return
                group: List[DecodeStream] = []
                lanes: List[int] = []
                cap = min(len(free), max(engine._prefill_buckets))
                while self._queue and len(group) < cap:
                    stream = self._queue.popleft()
                    if stream.expired():
                        if stream._expire() and self._metrics is not None:
                            self._metrics.record_deadline_expired()
                        continue
                    group.append(stream)
                    lanes.append(free[len(group) - 1])
                if not group:
                    continue
                t0_ns = time.perf_counter_ns()
                for stream, lane in zip(group, lanes):
                    self._lane_stream[lane] = stream
                    stream._role = "prefill"
                    if stream._t_dispatch_ns is None:
                        stream._t_dispatch_ns = t0_ns
                    if _trace.enabled() and stream.rid is not None:
                        _trace.event(
                            "disagg_prefill_dispatch",
                            rid=stream.rid,
                            attrs={"lane": lane},
                        )
            # Page allocation on the PREFILL pool (same split as the
            # base: bookkeeping under _lock, CoW + prefill outside). An
            # exhausted-pool stream requeues at the head while anything
            # at all is in flight ANYWHERE (busy lanes, parked
            # handoffs, active decode slots all eventually free
            # prefill pages); with the whole pipeline idle it could
            # never run — shed.
            plans = []
            admitted: List[DecodeStream] = []
            admitted_lanes: List[int] = []
            with self._lock:
                overflow = []
                for stream, lane in zip(group, lanes):
                    if self._lane_stream[lane] is not stream:
                        continue  # failed by close()/crash already
                    plan = engine.admit_slot(lane, stream.prompt, copy=False)
                    if plan is None:
                        overflow.append((stream, lane))
                    else:
                        plans.append(plan)
                        admitted.append(stream)
                        admitted_lanes.append(lane)
                overflow_lanes = [l for _, l in overflow]
                others_active = (
                    any(
                        s is not None and i not in overflow_lanes
                        for i, s in enumerate(self._lane_stream)
                    )
                    or bool(admitted)
                    or bool(self._parked)
                    or any(s is not None for s in self._slot_stream)
                )
                for stream, lane in reversed(overflow):
                    self._lane_stream[lane] = None
                    if others_active:
                        self._queue.appendleft(stream)
                    else:
                        if self._metrics is not None:
                            self._metrics.record_rejected()
                        stream._fail(RejectedError(
                            "prefill KV page pool exhausted with "
                            "nothing in flight to wait for: the prompt "
                            "needs more pages than the prefill role's "
                            "pool_pages can ever free — raise it or "
                            "shorten the prompt."
                        ))
            if not admitted:
                if overflow:
                    return
                continue
            group, lanes = admitted, admitted_lanes
            for plan in plans:
                cow = plan.pop("cow", None)
                if cow is not None:
                    engine.copy_page(*cow)
            cold = [
                i for i, p in enumerate(plans)
                if not p.get("shared_tokens")
            ]
            warm = [
                i for i, p in enumerate(plans) if p.get("shared_tokens")
            ]
            t0 = time.perf_counter()
            first = np.zeros(len(group), np.int32)
            if cold:
                out = engine.prefill(
                    [group[i].prompt for i in cold],
                    [lanes[i] for i in cold],
                )
                for i, tok in zip(cold, out):
                    first[i] = tok
            if warm:
                out = engine.prefill_warm(
                    [group[i].prompt for i in warm],
                    [lanes[i] for i in warm],
                    [int(plans[i]["shared_tokens"]) for i in warm],
                )
                for i, tok in zip(warm, out):
                    first[i] = tok
            dt_ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                now = time.perf_counter()
                delivered = 0
                for stream, lane, token in zip(group, lanes, first):
                    if self._lane_stream[lane] is not stream:
                        continue  # failed by close()/crash mid-dispatch
                    stream.ttft_ms = (now - stream._t_submit) * 1e3
                    if self._metrics is not None:
                        self._metrics.record_ttft(stream.ttft_ms)
                    engine.insert_prefix(lane, stream.prompt)
                    token = int(token)
                    # First token delivered AT PREFILL: TTFT is the
                    # prefill role's number; the transfer rides token
                    # 2's gap (the §22 trade).
                    stream._deliver(token)
                    delivered += 1
                    prompt_len = int(stream.prompt.shape[0])
                    reason = None
                    if stream._eos is not None and token == stream._eos:
                        reason = "eos"
                    elif len(stream._tokens) >= stream._max_new:
                        reason = "length"
                    elif prompt_len + 1 >= self._engine.token_limit:
                        # Decode-role capacity: the sequence could
                        # never grow there (same truncate-at-exactly-
                        # token_limit contract as single-mesh).
                        reason = "capacity"
                    if reason is not None:
                        # Done at its first token: never parks, never
                        # transfers.
                        stream._finish(reason)
                        self._free_lane(lane)
                        if _trace.enabled() and stream.rid is not None:
                            _trace.event(
                                "decode_stream_finish",
                                rid=stream.rid,
                                attrs={
                                    "lane": lane,
                                    "reason": reason,
                                    "tokens": len(stream._tokens),
                                },
                            )
                    else:
                        stream._role = "transfer"
                        self._parked.append(
                            (stream, lane, token, prompt_len)
                        )
                        if _trace.enabled() and stream.rid is not None:
                            _trace.event(
                                "disagg_prefill_park",
                                rid=stream.rid,
                                attrs={
                                    "lane": lane,
                                    "parked": len(self._parked),
                                },
                            )
                if self._metrics is not None:
                    self._metrics.record_prefill(dt_ms, delivered)
                    self._metrics.record_first_tokens(delivered)

    def _admit_decode(self) -> None:
        """DecodeQueue step: land parked handoffs into free decode
        slots, oldest first. Per handoff: adopt destination pages
        under ``_lock``, run the chaos checks + page transfer OUTSIDE
        it (device work), commit with the identity check, and only
        then release the source lane — the atomic refcount handoff."""
        from zookeeper_tpu.resilience import faults

        engine = self._engine
        spec = getattr(self, "_speculative", None)
        while True:
            with self._lock:
                if self._swap_pending is not None or not self._parked:
                    return
                free = [
                    i for i, s in enumerate(self._slot_stream) if s is None
                ]
                if not free:
                    return
                stream, lane, token, prompt_len = self._parked.popleft()
                slot = free[0]
                n_pages = engine.page_pool.pages_for(prompt_len)
                pages = engine.page_pool.adopt_slot(slot, n_pages)
                if pages is None:
                    # Decode pool exhausted: wait parked (the prefill
                    # pages stay resident — nothing to redo) while any
                    # decode slot can still free pages; with the slot
                    # array idle it could never land — shed.
                    if any(s is not None for s in self._slot_stream):
                        self._parked.appendleft(
                            (stream, lane, token, prompt_len)
                        )
                        return
                    if self._metrics is not None:
                        self._metrics.record_rejected()
                    stream._fail(RejectedError(
                        "decode KV page pool exhausted with no active "
                        "streams to wait for: the handoff needs more "
                        "pages than the decode role's pool_pages can "
                        "ever free — raise it or shorten the prompt."
                    ))
                    self._free_lane(lane)
                    continue
                # Reserve the slot BEFORE the device work so close()/
                # crash can see (and fail) the stream mid-transfer.
                self._slot_stream[slot] = stream
                self._slot_lengths[slot] = prompt_len
                stream._slot = slot
                src_pages = [
                    int(p)
                    for p in self._prefill_engine.page_pool.table[
                        lane, :n_pages
                    ]
                ]
            plan = faults.active()
            if plan is not None and plan.take_prefill_role_crash():
                self._on_prefill_crash(stream, lane, slot)
                continue
            try:
                self._transfer.move(src_pages, pages, rid=stream.rid)
            except PageTransferError as e:
                # Victim-only failure: unwind the adopted destination
                # pages, release the source lane, fail the one stream.
                # Both pools leak-free; every other stream unaffected.
                with self._lock:
                    if self._slot_stream[slot] is stream:
                        self._slot_stream[slot] = None
                        engine.release_slot(slot)
                    if self._lane_stream[lane] is stream:
                        self._free_lane(lane)
                    stream._fail(e)
                continue
            if spec is not None:
                # Seed the draft cache at DECODE admission (cold
                # prefill — the draft lives with the decode role; its
                # first-token output is discarded, the teacher's was
                # already delivered at the prefill role).
                spec.draft_engine.prefill([stream.prompt], [slot])
            with self._lock:
                if self._slot_stream[slot] is not stream:
                    # Failed by close()/crash mid-transfer; its slot
                    # pages were released there. Drop the source lane
                    # reference if it is still ours.
                    if self._lane_stream[lane] is stream:
                        self._free_lane(lane)
                    continue
                # Import landed: the source side releases LAST, so at
                # no instant were the pages unowned.
                if self._lane_stream[lane] is stream:
                    self._free_lane(lane)
                stream._role = "decode"
                if spec is not None:
                    self._draft_lengths[slot] = prompt_len
                    self._draft_pending[slot] = []
                self._slot_tokens[slot] = int(token)
                if _trace.enabled() and stream.rid is not None:
                    _trace.event(
                        "disagg_decode_admit",
                        rid=stream.rid,
                        attrs={"slot": slot, "pages": n_pages},
                    )

    # -- failure shapes ---------------------------------------------------

    def _on_prefill_crash(
        self, stream: DecodeStream, lane: int, slot: int
    ) -> None:
        """The prefill ROLE died mid-handoff
        (``FaultPlan.prefill_role_crash_at``): its device state — pool,
        lanes, in-flight handoffs — is gone wholesale. Reset the
        prefill engine (zero leaks by construction), fail every stream
        still on the prefill side cleanly (partials readable), unwind
        the victim's adopted decode pages, and keep the decode role
        serving its active slots untouched."""
        with self._lock:
            wrapped = WorkerCrashedError(
                "prefill role crashed mid-handoff (FaultPlan."
                "prefill_role_crash_at); this stream was failed "
                "cleanly (partial tokens in tokens_so_far) — resubmit "
                "to prefill on the recovered role."
            )
            victims = [stream]
            for rec in self._parked:
                if all(rec[0] is not v for v in victims):
                    victims.append(rec[0])
            self._parked.clear()
            for i, s in enumerate(self._lane_stream):
                if s is not None and all(s is not v for v in victims):
                    victims.append(s)
                self._lane_stream[i] = None
            # The role's pool is lost with the role: reset rather than
            # release-by-release (the host allocator and device pool
            # come back empty and consistent — leak_check() == 0).
            self._prefill_engine._reset_cache()
            if self._slot_stream[slot] is stream:
                self._slot_stream[slot] = None
                self._engine.release_slot(slot)
            for v in victims:
                v._fail(wrapped)
            if self._metrics is not None:
                self._metrics.record_worker_restart()
            _trace.event(
                "disagg_prefill_role_crash",
                attrs={"failed_streams": len(victims)},
            )
        _recorder.notify(
            "disagg_prefill_role_crash",
            attrs={"failed_streams": len(victims)},
        )

    def _on_crash(self, error: BaseException) -> None:
        """Whole-scheduler crash: the prefill side's streams fail with
        the same wrapped error the base gives queue/slot streams, lanes
        release their pages, then the base cleanup runs."""
        with self._lock:
            victims: List[DecodeStream] = []
            for rec in getattr(self, "_parked", ()):
                victims.append(rec[0])
            if getattr(self, "_parked", None) is not None:
                self._parked.clear()
            for i, s in enumerate(getattr(self, "_lane_stream", ())):
                if s is not None and all(s is not v for v in victims):
                    victims.append(s)
                self._lane_stream[i] = None
                self._prefill_engine.release_slot(i)
            wrapped = WorkerCrashedError(
                f"DisaggScheduler crashed ({error!r}); this stream was "
                "failed cleanly (partial tokens in tokens_so_far) — "
                "resubmit to run on the restarted scheduler."
            )
            wrapped.__cause__ = error
            for v in victims:
                v._fail(wrapped)
        super()._on_crash(error)

    def close(self, drain: bool = False) -> None:
        if getattr(self, "_engine", None) is None:
            return
        if drain:
            try:
                self.drain()
            except Exception:
                pass  # per-stream errors already delivered
        err = RuntimeError(
            "DisaggScheduler closed with streams pending."
        )
        with self._lock:
            for rec in self._parked:
                rec[0]._fail(err)
            self._parked.clear()
            for i, s in enumerate(self._lane_stream):
                if s is not None:
                    s._fail(err)
                self._lane_stream[i] = None
                self._prefill_engine.release_slot(i)
        super().close(drain=False)

    # -- loop hooks -------------------------------------------------------

    def _has_work(self) -> bool:
        with self._lock:
            return (
                bool(self._queue)
                or bool(self._parked)
                or any(s is not None for s in self._lane_stream)
                or any(s is not None for s in self._slot_stream)
            )

    def _expire_active(self) -> None:
        super()._expire_active()
        self._expire_parked()

    def _expire_parked(self) -> None:
        """Deadline sweep over the handoff queue (streams between the
        roles are as expirable as queued or active ones). Caller holds
        ``_lock`` (the ``_step_once`` sweep phase)."""
        now = time.perf_counter()
        if not any(rec[0].expired(now) for rec in self._parked):
            return
        kept = deque()
        for rec in self._parked:
            stream, lane = rec[0], rec[1]
            if stream.expired(now):
                if stream._expire() and self._metrics is not None:
                    self._metrics.record_deadline_expired()
                self._free_lane(lane)
            else:
                kept.append(rec)
        object.__setattr__(self, "_parked", kept)

    def _maybe_apply_swap(self) -> None:
        """One weight version per sequence, across BOTH roles: the swap
        waits for the queue/lanes/parked/slots pipeline to drain, then
        swaps the prefill engine (and drops its prefix cache — cached
        K/V belongs to the old weights) before the base applies the
        decode-role swap."""
        pending = getattr(self, "_swap_pending", None)
        if pending is None:
            return
        if self._parked or any(s is not None for s in self._lane_stream):
            return
        if any(s is not None for s in self._slot_stream):
            return
        params, model_state, _ = pending
        self._prefill_engine.swap_weights(params, model_state)
        self._prefill_engine.invalidate_prefix_cache()
        super()._maybe_apply_swap()

    # -- introspection ----------------------------------------------------

    def status(self) -> dict:
        """The single-mesh ``status()`` plus per-role sections: the
        decode numbers keep their inherited keys (dashboards reuse),
        ``prefill`` and ``transfer`` are the §22 additions."""
        out = super().status()
        pe = self._prefill_engine
        with self._lock:
            out["role_topology"] = "disagg"
            out["prefill"] = {
                "lanes": int(pe.slots),
                "busy_lanes": sum(
                    1 for s in self._lane_stream if s is not None
                ),
                "parked_handoffs": len(self._parked),
                "compiles": pe.compile_count,
                "recompiles_detected": pe.recompiles_detected,
                "kv_pool": pe.pool_status(),
            }
            out["transfer"] = self._transfer.status()
        return out
