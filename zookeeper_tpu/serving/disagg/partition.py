"""Role-aware partitioning: one host, two mesh slices (docs/DESIGN.md §22).

Disaggregated serving runs the two decode-subsystem programs on
DIFFERENT device slices: prefill (compute-bound, batched wide) on one,
the decode step (memory-bound, latency-critical) on the other. The
existing partitioners cannot express that — ``num_devices`` always
takes the FIRST N devices, so two of them would overlap. This module
adds the topology object:

- :class:`DisaggPartitioner` — owns two :class:`~zookeeper_tpu.parallel
  .partitioner.MeshPartitioner` roles pinned to disjoint device slices
  via ``with_devices`` (the programmatic seam added for exactly this).
  Device counts resolve at ``setup()``: explicit ``prefill_devices`` /
  ``decode_devices`` or an even split of the host. When the host
  cannot provide disjoint slices (the 1-device CPU tier-1 case) the
  roles OVERLAP from device 0 — functionally identical, flagged in
  ``describe()`` so an operator never mistakes the portable fallback
  for real disaggregation.

The class is itself a :class:`~zookeeper_tpu.parallel.partitioner.
Partitioner` delegating to the DECODE role (the latency-critical slice
is the service's "default" placement), so anything written against the
ABC — observability, resilience probes — keeps working unchanged.
"""

from typing import Any, Optional, Tuple

from zookeeper_tpu.core import ComponentField, Field, component
from zookeeper_tpu.parallel.partitioner import MeshPartitioner, Partitioner

__all__ = ["DisaggPartitioner"]


@component
class DisaggPartitioner(Partitioner):
    """Two-role device topology: a prefill mesh slice and a decode mesh
    slice over one host's devices (see module docstring)."""

    #: Devices for the prefill role (-1 = half the host, rounded down,
    #: at least 1).
    prefill_devices: int = Field(-1)
    #: Devices for the decode role (-1 = the rest of the host, at
    #: least 1).
    decode_devices: int = Field(-1)
    #: Per-role mesh partitioners (CLI-configurable mesh axes, e.g.
    #: ``partitioner.prefill_mesh.mesh_shape=(-1,2)``); their device
    #: lists are pinned HERE at setup — ``num_devices`` on the roles is
    #: ignored by construction.
    prefill_mesh: MeshPartitioner = ComponentField(MeshPartitioner)
    decode_mesh: MeshPartitioner = ComponentField(MeshPartitioner)

    # -- topology resolution ---------------------------------------------

    def setup(self) -> None:
        """Resolve the device split and build both role meshes.
        Idempotent."""
        if getattr(self, "_roles_ready", False):
            return
        import jax

        devices = list(jax.devices())
        n = len(devices)
        pn = int(self.prefill_devices)
        dn = int(self.decode_devices)
        if pn == 0 or dn == 0 or pn < -1 or dn < -1:
            raise ValueError(
                f"prefill_devices={pn} / decode_devices={dn} must be "
                ">= 1 per role (-1 = auto split)."
            )
        if pn < 0:
            pn = max(1, n // 2)
        if dn < 0:
            dn = max(1, n - pn)
        if pn > n or dn > n:
            raise ValueError(
                f"role sizes prefill={pn} / decode={dn} exceed the "
                f"host's {n} devices."
            )
        disjoint = pn + dn <= n
        if disjoint:
            prefill_devs = devices[:pn]
            decode_devs = devices[pn:pn + dn]
        else:
            # Overlapping fallback (e.g. the 1-device CPU host): both
            # roles from device 0. The page transfer degenerates to a
            # same-device move — every protocol step still runs, which
            # is exactly what the tier-1 certification needs.
            prefill_devs = devices[:pn]
            decode_devs = devices[:dn]
        self.prefill_mesh.with_devices(prefill_devs)
        self.decode_mesh.with_devices(decode_devs)
        self.prefill_mesh.setup()
        self.decode_mesh.setup()
        object.__setattr__(self, "_disjoint", disjoint)
        object.__setattr__(self, "_roles_ready", True)

    @property
    def prefill(self) -> MeshPartitioner:
        """The prefill role's partitioner (mesh built)."""
        self.setup()
        return self.prefill_mesh

    @property
    def decode(self) -> MeshPartitioner:
        """The decode role's partitioner (mesh built)."""
        self.setup()
        return self.decode_mesh

    @property
    def disjoint(self) -> bool:
        """Whether the two roles landed on disjoint device slices
        (False = the overlapping single-host fallback)."""
        self.setup()
        return bool(self._disjoint)

    def describe(self) -> dict:
        """The ``/statusz`` topology section: per-role device lists and
        whether the slices are genuinely disjoint."""
        self.setup()
        return {
            "disjoint": bool(self._disjoint),
            "prefill_devices": [
                str(d) for d in self.prefill_mesh.mesh.devices.flat
            ],
            "decode_devices": [
                str(d) for d in self.decode_mesh.mesh.devices.flat
            ],
        }

    # -- Partitioner ABC: delegate to the DECODE role --------------------
    #
    # The decode slice is the service's default placement (the
    # latency-critical role); code written against the ABC — probes,
    # ledger keys, resilience checks — sees that mesh. The prefill role
    # is reached explicitly via ``.prefill``.

    @property
    def mesh(self):
        return self.decode.mesh

    def prepare_model(self, model: Any) -> None:
        self.decode.prepare_model(model)

    def batch_sharding(self):
        return self.decode.batch_sharding()

    def slab_sharding(self):
        return self.decode.slab_sharding()

    def shard_state(self, state: Any) -> Any:
        return self.decode.shard_state(state)

    def state_sharding(self, state: Any) -> Any:
        return self.decode.state_sharding(state)

    def compile_step(self, step_fn, state, *, donate_state: bool = True):
        return self.decode.compile_step(
            step_fn, state, donate_state=donate_state
        )

    def compile_multi_step(
        self,
        multi_step_fn,
        state,
        *,
        donate_state: bool = True,
        donate_slab: bool = False,
    ):
        return self.decode.compile_multi_step(
            multi_step_fn,
            state,
            donate_state=donate_state,
            donate_slab=donate_slab,
        )

    def compile_eval(self, eval_fn, state):
        return self.decode.compile_eval(eval_fn, state)

    def variables_sharding(self, variables: Any) -> Any:
        return self.decode.variables_sharding(variables)

    def compile_forward(self, forward_fn, variables, *, batch_rows=None):
        return self.decode.compile_forward(
            forward_fn, variables, batch_rows=batch_rows
        )

    def decode_cache_axes(self) -> Tuple[Tuple[str, ...], Optional[str]]:
        return self.decode.decode_cache_axes()

    def decode_cache_sharding(self, cache: Any) -> Any:
        return self.decode.decode_cache_sharding(cache)

    def page_pool_sharding(self, pool: Any) -> Any:
        return self.decode.page_pool_sharding(pool)
