"""The disaggregated LM serving config: one checkpoint, two role
engines (docs/DESIGN.md §22).

``LMServingConfig`` with the topology split: the SAME weights bind
into a PREFILL engine (few lanes, wide ``prefill_buckets``, prefix
cache on — the compute-bound role) on one mesh slice and a DECODE
engine (the full slot array, ``prefill_buckets=(1,)`` — it never runs
prefill — prefix cache off, the memory-bound role) on another, joined
by a :class:`~zookeeper_tpu.serving.disagg.transfer.PageTransfer` and
scheduled by the :class:`~zookeeper_tpu.serving.disagg.scheduler.
DisaggScheduler`. Both engines run the paged KV layout — the handoff
unit is the page.

Everything else inherits: checkpoint/EMA selection, speculative
decoding (the draft lives with the DECODE role), the demo driver, the
observability endpoint (which gains ``prefill``/``transfer``/
``topology`` ``/statusz`` sections and the ``zk_transfer_*`` series),
and the one-JSON-line report (which gains ``role="disagg"`` and the
transfer keys).

CLI::

    python examples/serve_lm.py ServeLM --disagg checkpoint=/tmp/ckpt
    # role sizing:
    ... --disagg prefill_engine.slots=4 engine.slots=16 \\
        partitioner.prefill_devices=2 partitioner.decode_devices=6
"""

import logging
from typing import Any, Dict, Optional

from zookeeper_tpu.core import ComponentField, component
from zookeeper_tpu.parallel.partitioner import Partitioner
from zookeeper_tpu.serving.decode.engine import DecodeEngine
from zookeeper_tpu.serving.decode.scheduler import DecodeScheduler
from zookeeper_tpu.serving.decode.service import LMServingConfig
from zookeeper_tpu.serving.disagg.partition import DisaggPartitioner
from zookeeper_tpu.serving.disagg.scheduler import DisaggScheduler
from zookeeper_tpu.serving.disagg.transfer import PageTransfer

logger = logging.getLogger(__name__)

__all__ = ["DisaggServingConfig"]


@component
class DisaggServingConfig(LMServingConfig):
    """Disaggregated prefill/decode serving (see module docstring).
    Subclass with ``@task`` for a CLI entry point — ``examples/
    serve_lm.py --disagg``."""

    #: The role topology: two mesh slices (disjoint when the host has
    #: the devices; overlapping single-host fallback otherwise).
    partitioner: Partitioner = ComponentField(DisaggPartitioner)
    #: The DECODE role (the inherited ``engine`` slot, so every
    #: downstream report key keeps meaning "the serving engine"):
    #: paged by construction; prefill programs unused (admission
    #: arrives by page transfer), prefix cache off (adopted pages are
    #: private to their stream).
    engine: DecodeEngine = ComponentField(
        DecodeEngine, kv_layout="paged", prefix_cache=False
    )
    #: The PREFILL role: few lanes batched wide, prefix cache on (warm
    #: prompts skip prefill BEFORE the transfer, so shared pages are
    #: computed once and shipped many times).
    prefill_engine: DecodeEngine = ComponentField(
        DecodeEngine, kv_layout="paged", slots=4, prefill_buckets=(1, 2, 4)
    )
    #: The page mover (``transfer.host_bounce=True`` forces the
    #: portable host path for A/B).
    transfer: PageTransfer = ComponentField(PageTransfer)
    scheduler: DecodeScheduler = ComponentField(DisaggScheduler)

    # -- wiring ----------------------------------------------------------

    def _role_partitioners(self):
        """(prefill, decode) role partitioners: the DisaggPartitioner's
        slices, or the one configured partitioner for both roles when a
        user swapped in a non-role-aware one."""
        p = self.partitioner
        if hasattr(p, "prefill") and hasattr(p, "decode"):
            return p.prefill, p.decode
        return p, p

    def build_service(self):
        """Load weights ONCE, bind + warm both role engines, bind the
        transfer and the disaggregated scheduler. Returns ``(engine,
        scheduler)`` — the decode role, like the single-mesh config."""
        if self.weights not in ("auto", "ema", "raw"):
            raise ValueError(
                f"weights={self.weights!r} unknown; choose auto/ema/raw."
            )
        if self.requests < 0 or self.max_prompt < 1 or self.new_tokens < 1:
            raise ValueError(
                f"requests={self.requests} must be >= 0, max_prompt="
                f"{self.max_prompt} and new_tokens={self.new_tokens} "
                ">= 1."
            )
        for role, eng in (
            ("prefill", self.prefill_engine),
            ("decode", self.engine),
        ):
            if int(eng.prefill_chunk_tokens) > 0:
                # Chunked prefill is the SINGLE-mesh answer to prefill/
                # decode interference (docs/DESIGN.md §25); disagg
                # already isolates the roles on separate slices, so
                # chunking would only fragment the prefill role's
                # dispatches. Warn-degrade, mirroring the §20 posture.
                logger.warning(
                    "prefill_chunk_tokens=%d ignored on the disagg %s "
                    "role: disaggregation already isolates prefill "
                    "from decode (docs/DESIGN.md §25) — running "
                    "monolithic prefill.",
                    int(eng.prefill_chunk_tokens),
                    role,
                )
                # Post-configure components are immutable; the degrade
                # writes the instance value store directly (the same
                # bypass the component runtime itself uses) BEFORE
                # bind() reads the field.
                object.__getattribute__(
                    eng, "__component_values__"
                )["prefill_chunk_tokens"] = 0
        module, params, model_state = self._build_module_and_weights()
        self.partitioner.setup()
        prefill_part, decode_part = self._role_partitioners()
        self.prefill_engine.bind(
            module, params, model_state, partitioner=prefill_part
        )
        self.engine.bind(
            module, params, model_state, partitioner=decode_part
        )
        if self.warmup:
            self.prefill_engine.warmup()
            self.engine.warmup()
            # The handoff programs compile with the grid: export on
            # the prefill role, import on the decode role (each role
            # warms both directions' own half).
            self.prefill_engine.warmup_transfer()
            self.engine.warmup_transfer()
        self.transfer.bind(
            self.prefill_engine, self.engine, metrics=self.metrics
        )
        spec = self._resolve_speculative()
        self.scheduler.bind(
            self.prefill_engine,
            self.engine,
            self.transfer,
            metrics=self.metrics,
            speculative=spec,
        )
        if self.metrics_port >= 0 or self.flight_recorder_dir:
            try:
                if self.flight_recorder_dir:
                    self._start_flight_recorder()
                if self.metrics_port >= 0:
                    self._start_obs_server()
            except BaseException:
                self._teardown_service(suppress=True)
                raise
        return self.engine, self.scheduler

    # -- observability ----------------------------------------------------

    def _prefill_status(self) -> Dict[str, Any]:
        """``/statusz`` prefill-role section."""
        pe = self.prefill_engine
        sched = self.scheduler
        return {
            "lanes": int(pe.slots),
            "parked_handoffs": (
                sched.parked if hasattr(sched, "parked") else 0
            ),
            "compiles": pe.compile_count,
            "recompiles_detected": pe.recompiles_detected,
            "decode_attention": pe.decode_attention_flavor,
            "kv_pool": pe.pool_status(),
        }

    def _topology_status(self) -> Dict[str, Any]:
        p = self.partitioner
        return p.describe() if hasattr(p, "describe") else {}

    def _status_providers(self):
        out = super()._status_providers()
        out["prefill"] = self._prefill_status
        out["transfer"] = self.transfer.status
        out["topology"] = self._topology_status
        return out

    # -- reporting --------------------------------------------------------

    def finish_report(
        self,
        *,
        warm_compiles: int,
        n_requests: int,
        tokens: int,
        dt: float,
        writer_extra: Optional[Dict[str, float]] = None,
        result_extra: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """The single-mesh result line with the §22 keys: ``role``
        flips to "disagg" and the transfer totals/latency land
        unconditionally."""
        ts = self.transfer.status()
        p = self.partitioner
        extra = {
            "role": "disagg",
            "prefill_lanes": int(self.prefill_engine.slots),
            "prefill_compiles": self.prefill_engine.compile_count,
            "disjoint_roles": bool(getattr(p, "disjoint", False)),
            "transfer_handoffs": int(ts["handoffs_total"]),
            "transfer_pages": int(ts["pages_total"]),
            "transfer_bytes": int(ts["bytes_total"]),
            "transfer_host_bounces": int(ts["host_bounces"]),
            "transfer_ms_p50": float(ts["transfer_ms_p50"]),
            **(result_extra or {}),
        }
        return super().finish_report(
            warm_compiles=warm_compiles,
            n_requests=n_requests,
            tokens=tokens,
            dt=dt,
            writer_extra=writer_extra,
            result_extra=extra,
        )
