"""The LM decode service config: checkpoint -> warmed decode engine.

The ``ServingConfig`` counterpart for token streaming: point it at a
``save_model`` export or ``Checkpointer`` directory of a
``TransformerLM`` run (EMA-vs-raw selection identical), and
``build_service()`` returns a warmed :class:`DecodeEngine` +
:class:`DecodeScheduler` pair. ``run()`` is the demo/bench driver: a
deterministic synthetic prompt stream through the continuous-batching
loop, one JSON result line (tokens/s, TTFT percentiles, refill count,
compile counts) through the same MetricsWriter sinks — so
``python examples/serve_lm.py ServeLM checkpoint=...`` is an
end-to-end smoke of the whole decode subsystem.

The decode-attention flavor threads through the engine component
(``engine.decode_attention=auto|pallas|reference|module`` on the CLI —
docs/DESIGN.md §17): "auto" serves with the length-aware Pallas paged
decode kernel on TPU and the reference einsum elsewhere; the result
line and ``/statusz`` report the RESOLVED flavor plus the
``decode_mbu`` memory-bandwidth roofline.
"""

import json
import logging
import time
from typing import Any, Dict, Optional

from zookeeper_tpu.core import ComponentField, Field, component, pretty_print
from zookeeper_tpu.models.base import Model
from zookeeper_tpu.models.transformer import TransformerLM
from zookeeper_tpu.parallel.partitioner import (
    Partitioner,
    SingleDevicePartitioner,
)
from zookeeper_tpu.serving.decode.engine import DecodeEngine
from zookeeper_tpu.serving.decode.metrics import DecodeMetrics
from zookeeper_tpu.serving.decode.scheduler import DecodeScheduler
from zookeeper_tpu.serving.decode.speculative import SpeculativeDecoding
from zookeeper_tpu.serving.guardrails import OverloadGuard
from zookeeper_tpu.training.experiment import Experiment
from zookeeper_tpu.training.metrics import CompositeMetricsWriter, MetricsWriter

logger = logging.getLogger(__name__)

__all__ = ["LMServingConfig"]


@component
class LMServingConfig(Experiment):
    """Configurable token-streaming service over a causal LM.

    Subclass with ``@task`` for a CLI entry point — see
    ``examples/serve_lm.py``.
    """

    model: Model = ComponentField(TransformerLM)
    partitioner: Partitioner = ComponentField(SingleDevicePartitioner)
    engine: DecodeEngine = ComponentField(DecodeEngine)
    scheduler: DecodeScheduler = ComponentField(DecodeScheduler)
    metrics: DecodeMetrics = ComponentField(DecodeMetrics)
    writer: MetricsWriter = ComponentField(CompositeMetricsWriter)
    #: Speculative decoding (docs/DESIGN.md §18): ``speculative.
    #: enabled=True speculative.k=4 speculative.draft_checkpoint=...``
    #: serves the draft/verify schedule — token-identical to plain
    #: greedy decode, up to k+1 tokens per teacher dispatch. Resolved
    #: at bind; an unavailable draft (unreadable checkpoint,
    #: incompatible geometry) degrades LOUDLY to plain decode rather
    #: than failing the service.
    speculative: SpeculativeDecoding = ComponentField(SpeculativeDecoding)
    #: Overload guardrails (docs/DESIGN.md §24): ``guard.enabled=True``
    #: turns on predicted-miss admission (EWMA queue-wait + per-token
    #: service estimate vs each request's deadline ⇒ shed at submit
    #: with :class:`PredictedMissError`) and, with ``guard.
    #: brownout_after>0``, the brown-out degraded mode (capped
    #: ``max_new_tokens`` + speculation off, applied only at the
    #: drained-slot-array boundary). Off by default — zero behavior
    #: change unless asked for.
    guard: OverloadGuard = ComponentField(OverloadGuard)

    #: Deployment artifact: a ``save_model`` export or a full
    #: ``Checkpointer`` directory (latest step). None = fresh-init
    #: weights (compile/latency smoke without a training run).
    checkpoint: Optional[str] = Field(None)
    #: EMA-vs-raw weight selection (same contract as ServingConfig).
    weights: str = Field("auto")

    #: Model build geometry: the positional capacity the module is
    #: built with (prompt + generated tokens must fit) and the vocab.
    seq_len: int = Field(128)
    vocab_size: int = Field(256)
    seed: int = Field(0)

    #: Pre-compile the full prefill/decode program grid before traffic.
    warmup: bool = Field(True)
    #: Demo-driver knobs for ``run()``: request count, prompt-length
    #: range, and the per-request generation budget.
    requests: int = Field(32)
    max_prompt: int = Field(12)
    new_tokens: int = Field(16)
    verbose: bool = Field(True)
    #: Live observability endpoint: ``/metrics`` (every ``zk_decode_*``
    #: series) + ``/statusz`` decode section (active slots, queue
    #: depth, KV pages in use). -1 = off; 0 = ephemeral port.
    metrics_port: int = Field(-1)
    #: Flight recorder (docs/DESIGN.md §16): directory for rate-limited
    #: debug bundles on decode-worker crashes, recompiles, watchdog
    #: anomalies, fault injections and ``POST /debugz``. None = off.
    flight_recorder_dir: Optional[str] = Field(None)
    #: Minimum seconds between bundles (manual ``/debugz`` bypasses).
    flight_recorder_interval_s: float = Field(30.0)

    def build_service(self):
        """Load weights, bind + warm the engine, bind the scheduler.
        Returns ``(engine, scheduler)`` (also kept on self)."""
        if self.weights not in ("auto", "ema", "raw"):
            raise ValueError(
                f"weights={self.weights!r} unknown; choose auto/ema/raw."
            )
        if self.requests < 0 or self.max_prompt < 1 or self.new_tokens < 1:
            raise ValueError(
                f"requests={self.requests} must be >= 0, max_prompt="
                f"{self.max_prompt} and new_tokens={self.new_tokens} "
                ">= 1."
            )
        module, params, model_state = self._build_module_and_weights()
        self.partitioner.setup()
        self.engine.bind(
            module,
            params,
            model_state,
            partitioner=self.partitioner,
        )
        if self.warmup:
            self.engine.warmup()
        spec = self._resolve_speculative()
        self.guard.bind()
        self.scheduler.bind(
            self.engine,
            metrics=self.metrics,
            speculative=spec,
            guard=self.guard if self.guard.enabled else None,
        )
        if self.metrics_port >= 0 or self.flight_recorder_dir:
            try:
                if self.flight_recorder_dir:
                    self._start_flight_recorder()
                if self.metrics_port >= 0:
                    self._start_obs_server()
            except BaseException:
                self._teardown_service(suppress=True)
                raise
        return self.engine, self.scheduler

    def _build_module_and_weights(self):
        """Build the module and resolve its weights (checkpoint load or
        fresh init) — shared by this config and the disaggregated one,
        which binds the SAME weights into two role engines."""
        module = self.model.build((self.seq_len,), self.vocab_size)
        if self.checkpoint:
            import jax

            from zookeeper_tpu.training.checkpoint import (
                load_inference_model,
            )

            abstract = jax.eval_shape(
                lambda: self.model.initialize(
                    module, (self.seq_len,), seed=self.seed
                )
            )
            params, model_state = load_inference_model(
                self.checkpoint,
                weights=self.weights,
                params_like=abstract[0],
                model_state_like=abstract[1],
            )
        else:
            params, model_state = self.model.initialize(
                module, (self.seq_len,), seed=self.seed
            )
        return module, params, model_state

    def _resolve_speculative(self) -> Optional[SpeculativeDecoding]:
        """Resolve ``speculative`` at bind (docs/DESIGN.md §18): build
        the draft module from ``speculative.draft_model`` at the
        teacher's seq_len/vocab, load ``draft_checkpoint`` (EMA/raw per
        ``draft_weights``) or fresh-init when none is given (program-
        shape smoke — acceptance will be ~chance, flagged loudly), and
        bind the draft engine. An UNAVAILABLE draft — unreadable
        checkpoint, incompatible geometry — degrades LOUDLY to plain
        decode: the service stays up, the warning says why speculation
        is off. Returns the bound binding or None."""
        sp = self.speculative
        if not sp.enabled:
            return None
        draft_module = sp.draft_model.build((self.seq_len,), self.vocab_size)
        try:
            if sp.draft_checkpoint:
                import jax

                from zookeeper_tpu.training.checkpoint import (
                    load_inference_model,
                )

                abstract = jax.eval_shape(
                    lambda: sp.draft_model.initialize(
                        draft_module, (self.seq_len,), seed=self.seed
                    )
                )
                draft_params, draft_state = load_inference_model(
                    sp.draft_checkpoint,
                    weights=sp.draft_weights,
                    params_like=abstract[0],
                    model_state_like=abstract[1],
                )
            else:
                logger.warning(
                    "speculative.enabled with no draft_checkpoint: "
                    "serving a FRESH-INIT draft (program-shape smoke "
                    "only — acceptance will be ~chance; point "
                    "speculative.draft_checkpoint at the distilled "
                    "student for real speedup)"
                )
                draft_params, draft_state = sp.draft_model.initialize(
                    draft_module, (self.seq_len,), seed=self.seed
                )
            return sp.bind(
                self.engine,
                draft_module,
                draft_params,
                draft_state,
                partitioner=self.partitioner,
            )
        except (OSError, ValueError) as e:
            # Degrade loudly: a missing/unreadable/mismatched draft
            # must not take the TEACHER service down — but silent
            # plain-decode-with-spec-configured would misreport every
            # capacity plan built on the expected speedup.
            logger.warning(
                "speculative decoding DISABLED — draft unavailable "
                "(%s); serving plain greedy decode", e,
            )
            if self.verbose:
                print(
                    f"speculative decoding disabled: {e}", flush=True
                )
            return None

    def _request_log_status(self):
        """``/statusz`` + bundle section: the recent terminal-stream
        tail (rid, timestamps, outcome — docs/DESIGN.md §16)."""
        log = self.scheduler.request_log
        return log.as_status() if log is not None else {}

    def _status_providers(self):
        """Named ``/statusz`` (+ flight-recorder bundle) sections. The
        disaggregated config extends this with per-role sections."""
        return {
            "decode": self.scheduler.status,
            "requests": self._request_log_status,
            "guardrails": self.guard.status,
        }

    def _start_flight_recorder(self):
        from zookeeper_tpu.observability import recorder as _recorder
        from zookeeper_tpu.observability.registry import default_registry

        rec = _recorder.arm(
            self.flight_recorder_dir,
            registries=[
                default_registry(),
                self.metrics.registry,
                self.guard.registry,
            ],
            status_providers=self._status_providers(),
            request_logs={"decode": self.scheduler.request_log},
            min_interval_s=self.flight_recorder_interval_s,
        )
        object.__setattr__(self, "flight_recorder", rec)
        if self.verbose:
            print(
                f"flight recorder armed: {self.flight_recorder_dir}",
                flush=True,
            )
        return rec

    def _stop_flight_recorder(self):
        from zookeeper_tpu.observability import recorder as _recorder

        rec = getattr(self, "flight_recorder", None)
        if rec is not None:
            object.__setattr__(self, "flight_recorder", None)
            _recorder.disarm(rec)

    def _start_obs_server(self):
        from zookeeper_tpu.observability import (
            DeviceProbe,
            ObservabilityServer,
        )
        from zookeeper_tpu.observability.registry import default_registry

        server = ObservabilityServer(
            [
                default_registry(),
                self.metrics.registry,
                self.guard.registry,
            ],
            port=self.metrics_port,
            status_providers=self._status_providers(),
        )
        server.start()
        object.__setattr__(self, "obs_server", server)
        probe = DeviceProbe()
        probe.poll_once()
        probe.start()
        object.__setattr__(self, "obs_probe", probe)
        if self.verbose:
            print(
                f"observability endpoint: {server.url}/metrics",
                flush=True,
            )
        return server

    def _teardown_service(self, *, suppress: bool = False) -> None:
        """The ONE teardown sequence (endpoint port, device probe,
        scheduler worker) shared by every exit path — the
        ``run_teardown_steps`` contract ``ServingConfig`` uses."""
        from zookeeper_tpu.serving.service import run_teardown_steps

        steps = []
        server = getattr(self, "obs_server", None)
        if server is not None:
            object.__setattr__(self, "obs_server", None)
            steps.append(server.stop)
        probe = getattr(self, "obs_probe", None)
        if probe is not None:
            object.__setattr__(self, "obs_probe", None)
            steps.append(probe.stop)
        steps.append(self._stop_flight_recorder)
        steps.append(self.scheduler.close)
        run_teardown_steps(steps, suppress=suppress)

    def finish_report(
        self,
        *,
        warm_compiles: int,
        n_requests: int,
        tokens: int,
        dt: float,
        writer_extra: Optional[Dict[str, float]] = None,
        result_extra: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """The one reporting path: metrics snapshot through the writer,
        one JSON result line, teardown."""
        tokens_per_sec = tokens / dt if dt > 0 else 0.0
        snapshot = self.metrics.emit(
            self.writer,
            step=0,
            extra={"tokens_per_sec": tokens_per_sec, **(writer_extra or {})},
        )
        self.writer.flush()
        result = {
            **{k: round(float(v), 4) for k, v in snapshot.items()},
            "model": type(self.model).__name__,
            "weights": self.weights,
            "slots": int(self.engine.slots),
            "seq_buckets": [int(s) for s in self.engine.seq_buckets],
            "kv_capacity": self.engine.capacity,
            # The RESOLVED cache-attention flavor (docs/DESIGN.md §17):
            # "pallas" = the length-aware paged decode kernel,
            # "reference" = the oracle einsum (auto-selected off-TPU or
            # degraded on unsupported geometry).
            "decode_attention": self.engine.decode_attention_flavor,
            "decode_mbu": round(self.engine.decode_mbu, 4),
            # Paged-KV vitals (docs/DESIGN.md §20): the layout that
            # actually served, pool fill and prefix-cache hit rate
            # (both -1/absent under the slot layout).
            "kv_layout": str(self.engine.kv_layout),
            **(
                {
                    "kv_pool_fill": round(
                        self.engine.page_pool.used_pages
                        / self.engine.page_pool.num_pages,
                        4,
                    ),
                    "prefix_cache_hit_rate": round(
                        self.engine.page_pool.prefix_hit_rate, 4
                    ),
                }
                if self.engine.paged
                else {}
            ),
            # Speculative schedule (docs/DESIGN.md §18): the RESOLVED
            # state (config-enabled but draft-unavailable degrades to
            # False here — the result line reports what actually
            # served), k, and the live acceptance rate.
            "speculative": (
                getattr(self.scheduler, "_speculative", None) is not None
            ),
            "spec_k": (
                int(self.scheduler._speculative.k)
                if getattr(self.scheduler, "_speculative", None) is not None
                else 0
            ),
            # Unconditional when speculation serves (-1 = no window ran
            # yet); the snapshot merge above only carries it once a
            # window committed — scripts parsing the README'd key must
            # never find it absent on a speculative serve.
            **(
                {
                    "spec_acceptance_rate": round(
                        self.scheduler._speculative.acceptance_rate, 4
                    )
                }
                if getattr(self.scheduler, "_speculative", None) is not None
                else {}
            ),
            # Serving-role topology (docs/DESIGN.md §22): single-mesh
            # serves everything on the decode role with nothing to
            # transfer; the disaggregated config overrides all three
            # via result_extra. The keys are UNCONDITIONAL so scripts
            # parsing the result line never branch on topology.
            "role": "decode",
            "transfer_pages": 0,
            "transfer_ms_p50": -1.0,
            "compiles": self.engine.compile_count,
            "recompiles_after_warmup": (
                self.engine.compile_count - warm_compiles
            ),
            "requests": n_requests,
            "generated_tokens": tokens,
            "tokens_per_sec": round(tokens_per_sec, 1),
            **(result_extra or {}),
        }
        if self.verbose:
            print(json.dumps(result), flush=True)
        self._teardown_service()
        return result

    def run(self) -> Dict[str, Any]:
        """Serve a deterministic synthetic prompt stream and report."""
        import numpy as np

        if self.verbose:
            print(pretty_print(self), flush=True)
        engine, scheduler = self.build_service()
        try:
            warm_compiles = engine.compile_count
            rng = np.random.default_rng(self.seed)
            max_prompt = min(self.max_prompt, engine.max_prompt)
            t0 = time.perf_counter()
            streams = []
            for _ in range(self.requests):
                n = int(rng.integers(1, max_prompt + 1))
                prompt = rng.integers(
                    1, self.vocab_size, size=n
                ).astype(np.int32)
                streams.append(
                    scheduler.submit(
                        prompt, max_new_tokens=self.new_tokens
                    )
                )
            scheduler.drain()
            dt = time.perf_counter() - t0
            tokens = 0
            for stream in streams:
                out = stream.result()
                assert out.shape[0] >= 1, out.shape
                tokens += int(out.shape[0])
        except BaseException:
            self._teardown_service(suppress=True)
            raise
        return self.finish_report(
            warm_compiles=warm_compiles,
            n_requests=self.requests,
            tokens=tokens,
            dt=dt,
        )
