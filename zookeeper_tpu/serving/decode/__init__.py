"""Continuous-batching autoregressive LM decode (docs/DESIGN.md §15).

The token-streaming half of the serving stack — the ROADMAP's
"millions-of-users" interactive workload:

- :mod:`~zookeeper_tpu.serving.decode.cache` — paged/ring KV-cache
  state: per-layer ``[slots, capacity, heads, head_dim]`` buffers,
  device-resident, slots sharded on the data axes and heads on the
  model axis via the Partitioner rule tables.
- :class:`DecodeEngine` — the two compiled programs: a bucketed
  ``prefill`` (writes a request's KV pages, emits its first token) and
  ONE ``decode_step`` (one token per slot over the full slot array),
  AOT-warmed with the forward engine's zero-recompile discipline and
  ledgered in the ProgramLedger.
- :class:`DecodeScheduler` — slot-refill continuous batching: a
  finished sequence's slot is refilled from the queue without draining
  or recompiling; deadlines/shedding/crash-recovery reuse the PR 4
  machinery; ``generate()`` / :class:`DecodeStream` surface streaming
  results; ``request_swap`` applies weight hot-swaps at slot-array
  drain boundaries (one weight version per sequence).
- :class:`DecodeMetrics` — TTFT + per-token latency histograms, token
  counters, slot-occupancy and KV-page gauges (``zk_decode_*``).
- :class:`LMServingConfig` — the config-system citizen tying model +
  checkpoint + engine + scheduler into a CLI task
  (``examples/serve_lm.py``).
- :mod:`~zookeeper_tpu.serving.decode.pages` — TRUE paged KV
  (docs/DESIGN.md §20, ``engine.kv_layout="paged"``): a SHARED device
  page pool with per-slot page tables as runtime operands
  (:class:`PagePool` — free-list/refcount allocator), a radix prefix
  cache over prompt prefixes with copy-on-write at the divergence
  point (:class:`RadixPrefixCache` — warm-prefix admissions skip
  prefill for shared pages), and optional int8 KV quantization with
  per-row scales dequantized inside the attention read.
- :class:`SpeculativeDecoding` — the draft/verify schedule
  (docs/DESIGN.md §18): a small draft model proposes ``k`` tokens per
  slot, one teacher ``decode_verify`` dispatch scores the whole window
  (multi-token KV append + rollback-by-length), greedy acceptance
  keeps the longest prefix match — certified token-identical to plain
  greedy decode at up to ``k + 1`` tokens per teacher dispatch.
"""

from zookeeper_tpu.serving.decode.cache import (
    allocate_kv_cache,
    append_kv_rows,
    kv_cache_bytes,
    pages_in_use,
)
from zookeeper_tpu.serving.decode.engine import DecodeEngine
from zookeeper_tpu.serving.decode.pages import (
    PagePool,
    RadixPrefixCache,
    allocate_page_pool,
    page_pool_bytes,
)
from zookeeper_tpu.serving.decode.metrics import DecodeMetrics
from zookeeper_tpu.serving.decode.scheduler import (
    DecodeScheduler,
    DecodeStream,
)
from zookeeper_tpu.serving.decode.service import LMServingConfig
from zookeeper_tpu.serving.decode.speculative import SpeculativeDecoding

__all__ = [
    "DecodeEngine",
    "DecodeMetrics",
    "DecodeScheduler",
    "DecodeStream",
    "LMServingConfig",
    "PagePool",
    "RadixPrefixCache",
    "SpeculativeDecoding",
    "allocate_kv_cache",
    "allocate_page_pool",
    "append_kv_rows",
    "kv_cache_bytes",
    "page_pool_bytes",
    "pages_in_use",
]
