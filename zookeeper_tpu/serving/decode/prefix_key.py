"""Shared page-chunk prefix keying (docs/DESIGN.md §20, §23).

The :class:`~zookeeper_tpu.serving.decode.pages.RadixPrefixCache` keys
its trie on FULL ``page_size`` token chunks (one node = one page) with
a longest-common-prefix partial tail, and the fleet router
(docs/DESIGN.md §23) must predict that trie's match length WITHOUT
holding any pages: a router that chunks or walks differently routes
requests to replicas that are not actually warm, silently destroying
the §20 TTFT win. This module is the single source of truth both sides
consume:

- :func:`common_prefix` / :func:`walk_match` / :func:`walk_insert` —
  the chunking + match/insert walks, shared verbatim by the cache's
  ``lookup``/``insert`` and the router's :class:`PrefixIndex`, so the
  two CANNOT drift (the parity test in ``tests/serving/test_fleet.py``
  pins predicted == actual on top).
- :class:`PrefixIndex` — the pageless mirror of the trie: the router
  keeps one per replica, ``observe()``-ing every prompt it routes
  there and ``match()``-ing candidate prompts to predict how many
  tokens that replica's REAL cache would serve warm.

Any node object with ``.chunk`` (a token tuple) and ``.children``
(a ``{chunk_tuple: node}`` dict) can ride the walks — the cache's
page-holding nodes and the index's bare nodes both qualify.
"""

from typing import Any, Callable, List, Sequence, Tuple

__all__ = [
    "PrefixIndex",
    "common_prefix",
    "walk_insert",
    "walk_match",
]


def common_prefix(a: Sequence[int], b: Sequence[int]) -> int:
    """Length of the longest common prefix of two token sequences."""
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


def walk_match(root: Any, tokens: Sequence[int], page_size: int):
    """The ONE match walk (cache lookup == router prediction): exact
    full-``page_size``-chunk descents from ``root``, then the longest
    common prefix against any child's chunk for the partial tail.
    Returns ``(t, visited)`` — the first ``t`` tokens are covered by
    the ``visited`` nodes in walk order (the last may cover ``t``
    only partially — the cache's CoW case)."""
    ps = int(page_size)
    node = root
    visited: List[Any] = []
    t = 0
    n = len(tokens)
    while t + ps <= n:
        child = node.children.get(tuple(tokens[t:t + ps]))
        if child is None:
            break
        visited.append(child)
        t += ps
        node = child
    rest = tokens[t:]
    if rest:
        best, bestq = None, 0
        for child in node.children.values():
            q = common_prefix(child.chunk, rest)
            if q > bestq:
                best, bestq = child, q
        if best is not None:
            visited.append(best)
            t += bestq
    return t, visited


def walk_insert(
    root: Any,
    tokens: Sequence[int],
    page_size: int,
    make_node: Callable[[Tuple[int, ...], int, Any], Any],
    *,
    tail: bool = True,
):
    """The ONE insert walk: descend/create one node per FULL chunk
    (``make_node(chunk, chunk_index, parent)`` builds missing ones),
    plus the partial tail chunk when ``tail`` is set (the cache skips
    it when it has no page covering those positions). Returns
    ``[(node, created), ...]`` in walk order."""
    ps = int(page_size)
    tokens = [int(x) for x in tokens]
    node = root
    out: List[Tuple[Any, bool]] = []
    n_full = len(tokens) // ps
    for i in range(n_full):
        chunk = tuple(tokens[i * ps:(i + 1) * ps])
        child = node.children.get(chunk)
        created = child is None
        if created:
            child = make_node(chunk, i, node)
            node.children[chunk] = child
        out.append((child, created))
        node = child
    rest = tuple(tokens[n_full * ps:])
    if rest and tail:
        child = node.children.get(rest)
        created = child is None
        if created:
            child = make_node(rest, n_full, node)
            node.children[rest] = child
        out.append((child, created))
    return out


class _IndexNode:
    __slots__ = ("chunk", "children")

    def __init__(self, chunk: Tuple[int, ...]) -> None:
        self.chunk = chunk
        self.children = {}


class PrefixIndex:
    """Pageless mirror of the radix prefix-cache trie.

    The fleet router keeps one per replica: every prompt it routes
    there is ``observe()``-d (the replica's cache will insert exactly
    that prompt's pages after prefill), and ``match()`` walks the SAME
    chunking/keying the real cache uses, so the returned length is the
    router's best prediction of the replica's actual warm match.

    Predictions are optimistic by construction — the real cache evicts
    under pool pressure and invalidates on weight swaps while the
    index does not — which only costs a colder-than-predicted route,
    never a wrong answer. ``max_nodes`` bounds router memory: past it
    the index resets to empty (counted in ``resets``) and rewarms from
    subsequent traffic, mirroring a cache that evicted everything.
    """

    def __init__(self, page_size: int, max_nodes: int = 65536) -> None:
        if page_size < 1:
            raise ValueError(f"page_size={page_size} must be >= 1.")
        if max_nodes < 1:
            raise ValueError(f"max_nodes={max_nodes} must be >= 1.")
        self.page_size = int(page_size)
        self.max_nodes = int(max_nodes)
        self._root = _IndexNode(())
        self.nodes = 0
        self.resets = 0

    def observe(self, tokens: Sequence[int]) -> int:
        """Record a prompt routed to this replica (full chunks + the
        partial tail — exactly what the cache's ``insert_prefix``
        caches after prefill). Returns new nodes created."""
        created = sum(
            1
            for _, was_created in walk_insert(
                self._root,
                tokens,
                self.page_size,
                lambda chunk, i, parent: _IndexNode(chunk),
            )
            if was_created
        )
        self.nodes += created
        if self.nodes > self.max_nodes:
            self.clear()
            self.resets += 1
        return created

    def match(self, tokens: Sequence[int]) -> int:
        """Predicted warm match length for ``tokens`` — the ``t`` the
        replica's real ``RadixPrefixCache.lookup`` would return."""
        tokens = [int(x) for x in tokens]
        t, _ = walk_match(self._root, tokens, self.page_size)
        return t

    def predict(self, tokens: Sequence[int]) -> int:
        """Predicted SHARED tokens at admission: the match, capped at
        ``len(tokens) - 1`` exactly like ``PagePool.assign_prompt``
        (the final token is always recomputed so the first-emission
        logits exist)."""
        n = len(tokens)
        if n == 0:
            return 0
        return min(self.match(tokens), n - 1)

    def clear(self) -> None:
        self._root = _IndexNode(())
        self.nodes = 0
