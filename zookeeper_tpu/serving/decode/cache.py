"""KV-cache state for the continuous-batching decode engine.

Layout (docs/DESIGN.md §15): per transformer layer, one ``k`` and one
``v`` buffer of shape ``[slots, capacity, heads, head_dim]`` in the
model's compute dtype, carried as DEVICE-RESIDENT engine state and
donated through every prefill/decode dispatch (the update is in-place;
the cache never round-trips the host). ``capacity`` is page-aligned
(rounded up to a multiple of ``page_size``); the pages of one slot are
contiguous — a ring of SLOTS rather than an indirection table of
pages: zero indirection on the hot path, at the cost of per-slot
worst-case provisioning. The indirection step now EXISTS as the
sibling layout (``pages.py`` / ``DecodeEngine.kv_layout="paged"``,
docs/DESIGN.md §20 — shared page pool, page tables as runtime
operands, prefix reuse with copy-on-write, int8 quantization); THIS
module remains the default and the right choice when slots × capacity
fits HBM and prompts share nothing (§20's decision rule). The paged
decode-attention kernel (§17, ``ops.paged_decode_attention``)
consumes this layout AS IS: it walks a slot's contiguous pages in
page-nested blocks and stops at the slot's length, so the
length-bounded HBM read needed no layout change. Page
granularity also does real work host-side: ``pages_in_use`` is the
occupancy number the ``zk_decode_kv_pages_in_use`` gauge and
``/statusz`` report, and ``kv_cache_bytes`` feeds the
``zk_decode_kv_bytes`` gauge + the per-slot ``/statusz`` numbers.

Validity invariant (the slot-refill masking contract): a slot's cache
row ``j`` is meaningful iff ``j < length`` for that slot's CURRENT
occupant. Prefill writes rows ``[0, seq_bucket)`` (rows past the true
prompt length hold padding-token garbage), each decode step writes row
``length`` then advances ``length`` — so garbage rows are always at
``j >= length`` and the decode attention masks them
(``ops.cached_attention``). Refilling a slot therefore needs NO cache
zeroing: the new occupant's prefill overwrites rows up to its bucket
and its length masks everything beyond.

Multi-token append (docs/DESIGN.md §18): the speculative-decode verify
program writes ``w`` rows per slot in ONE dispatch —
:func:`append_kv_rows` is the primitive, a per-slot
``dynamic_update_slice`` along the capacity axis at each slot's
``length`` (the write expressed as "rows at an offset", not "the next
ring position" — exactly the shape the §20 paged layout's
table-resolved scatter generalizes). Rollback rides the SAME validity invariant, by
construction: a rejected draft suffix is "un-appended" simply by not
advancing ``length`` past the accepted prefix — the rejected rows sit
at ``j >= length`` where every attention path masks them and every
later append/step overwrites them before they could ever be attended.
The paged-decode-kernel poisoned-row tests (§17) certify exactly this
garbage-rows-beyond-length harmlessness as an equality.
"""

import math
from typing import Any, Tuple

__all__ = [
    "allocate_kv_cache",
    "append_kv_rows",
    "kv_cache_bytes",
    "pages_in_use",
]


def allocate_kv_cache(
    num_layers: int,
    slots: int,
    capacity: int,
    num_heads: int,
    head_dim: int,
    dtype: Any,
) -> Tuple[dict, ...]:
    """Zero-initialized KV cache pytree: a per-layer tuple of
    ``{"k", "v"}`` buffers ``[slots, capacity, heads, head_dim]``.
    Returned on the default device; the engine places it under the
    partitioner's decode-cache sharding."""
    import jax.numpy as jnp

    if slots < 1 or capacity < 1:
        raise ValueError(
            f"KV cache needs slots >= 1 and capacity >= 1, got "
            f"slots={slots}, capacity={capacity}."
        )
    shape = (slots, capacity, num_heads, head_dim)
    return tuple(
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(num_layers)
    )


def append_kv_rows(cache_buf, rows, starts):
    """Append ``w`` new KV rows per slot in one traced op: a vmapped
    ``dynamic_update_slice`` writing ``rows [slots, w, heads, head_dim]``
    into ``cache_buf [slots, capacity, heads, head_dim]`` at each slot's
    ``starts [slots]`` offset along the capacity axis (docs/DESIGN.md
    §18). The start index is clamped to ``capacity - w`` (standard DUS
    semantics) so an idle/garbage slot's write stays in bounds; CALLERS
    must guarantee active slots satisfy ``start + w <= capacity`` (the
    scheduler's speculation-eligibility check) — a clamped active write
    would land on live rows. Which of the ``w`` rows are *valid* is not
    this function's business: validity is ``j < length``, and rollback
    of a rejected suffix is just not advancing ``length`` (module
    docstring)."""
    import jax
    import jax.numpy as jnp

    w = rows.shape[1]
    starts = jnp.clip(starts, 0, cache_buf.shape[1] - w)
    return jax.vmap(
        lambda buf, upd, s: jax.lax.dynamic_update_slice(
            buf, upd, (s, 0, 0)
        )
    )(cache_buf, rows.astype(cache_buf.dtype), starts)


def kv_cache_bytes(
    num_layers: int,
    slots: int,
    capacity: int,
    num_heads: int,
    head_dim: int,
    itemsize: int,
) -> int:
    """Total HBM the cache occupies (k + v, all layers) — the decode
    engine's capacity-planning number (docs/DESIGN.md §15 cost model)."""
    return 2 * num_layers * slots * capacity * num_heads * head_dim * itemsize


def pages_in_use(lengths, page_size: int) -> int:
    """KV pages currently holding live tokens: ``sum(ceil(len /
    page_size))`` over the ACTIVE slots' lengths. Host-side accounting
    only (the gauge/statusz number) — storage itself is provisioned at
    full capacity per slot."""
    if page_size < 1:
        raise ValueError(f"page_size={page_size} must be >= 1.")
    return int(sum(math.ceil(int(n) / page_size) for n in lengths if n > 0))
