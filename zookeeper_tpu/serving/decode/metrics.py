"""Decode-path observability: TTFT, per-token latency, slot occupancy.

The decode analogue of :class:`~zookeeper_tpu.serving.metrics.\
ServingMetrics`, built the same way on the typed registry
(docs/DESIGN.md §13): every lifetime total is a Counter, every sampled
series feeds a bounded window (exact ``np.percentile`` snapshots) plus
a fixed-bucket Histogram (live ``/metrics`` scraping), recorders are
O(1) and thread-safe. Every instrument renders as ``zk_decode_*`` in
Prometheus text exposition — the CI scrape smoke asserts the whole
family.

The tracked quantities are the decode cost model's levers
(docs/DESIGN.md §15):

- ``zk_decode_ttft_ms`` — submit-to-first-token wall time (prefill
  queue wait + the bucketed prefill dispatch): the interactive-latency
  number, dominated by slot availability under load.
- ``zk_decode_token_ms`` — wall time of one decode dispatch (one token
  for EVERY active slot): the steady-state streaming rate; tokens/s =
  active_slots / token_ms.
- ``zk_decode_active_slots`` / ``zk_decode_slot_occupancy`` — how full
  the slot array runs; sustained occupancy 1.0 with queue depth > 0
  means the slot array, not the chip, is the bottleneck (add slots).
- ``zk_decode_kv_pages_in_use`` — live KV pages across active slots
  (page-granular occupancy of the provisioned cache HBM).

The speculative-decode family (docs/DESIGN.md §18) deliberately renders
under its own ``zk_spec_*`` prefix (the schedule spans two engines, not
just the decode path): ``zk_spec_draft_tokens_total`` /
``zk_spec_accepted_tokens_total`` lifetime counters (their ratio is the
acceptance rate — the one number that decides whether speculation
pays), the live ``zk_spec_acceptance_rate`` gauge, and the
``zk_spec_accept_length`` per-window histogram (how many of the ``k``
drafts each verify accepted: a mass at 0 means the draft disagrees with
the teacher; a mass at ``k`` means ``k`` could go higher).
"""

from collections import deque
from typing import Dict, Mapping, Optional

import numpy as np

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.observability.registry import (
    DEFAULT_MS_BUCKETS,
    MetricsRegistry,
)
from zookeeper_tpu.serving.metrics import (
    _emit_snapshot,
    _get_or_build_obs,
    _observe_sample,
    _reset_obs,
    _window_series,
)

__all__ = ["DecodeMetrics"]

_PREFIX = "zk_decode_"

#: Lifetime counters, in ``totals`` reporting order.
_COUNTER_NAMES = (
    # Generated tokens delivered to streams (the throughput numerator).
    "tokens_total",
    "requests_total",
    # Prefill dispatches (slot admissions — continuous-batching refills
    # included; requests_total - slots at steady state ~= refills).
    "prefills_total",
    # Decode dispatches (each serves every active slot one token).
    "decode_steps_total",
    # PR 4 admission-control family.
    "rejected_total",
    "deadline_expired_total",
    "worker_restarts_total",
    "weight_swaps_total",
)

#: Chunked-prefill counters (docs/DESIGN.md §25): registered under the
#: ``zk_prefill_`` prefix (the chunk schedule is an admission-side
#: concern, like ``zk_prefix_`` is the cache's); reported in ``totals``
#: after the decode family.
_CHUNK_COUNTER_NAMES = ("prefill_chunks_total",)

#: Speculative-decode counters: registered under the ``zk_spec_``
#: prefix (NOT ``zk_decode_``); reported in ``totals`` after the
#: decode family.
_SPEC_COUNTER_NAMES = (
    "spec_draft_tokens_total",
    "spec_accepted_tokens_total",
)

#: Disaggregated page-handoff counters (``zk_transfer_`` prefix);
#: reported in ``totals`` after the spec family.
_TRANSFER_COUNTER_NAMES = (
    "transfer_handoffs_total",
    "transfer_pages_total",
    "transfer_bytes",
)

#: Accept-length histogram buckets: counts of accepted drafts per
#: verify window (small ints, not milliseconds).
_SPEC_ACCEPT_BUCKETS = (0, 1, 2, 3, 4, 6, 8, 12, 16)


@component
class DecodeMetrics:
    """Bounded-window aggregator for decode samples (see module
    docstring); API shape mirrors ``ServingMetrics``."""

    #: Samples retained per series; percentiles reduce this window.
    window: int = Field(4096)

    # -- lazy state ------------------------------------------------------

    def _obs(self) -> dict:
        return _get_or_build_obs(self, self._build_obs)

    def _build_obs(self) -> dict:
        registry = MetricsRegistry()
        return {
            "registry": registry,
            "counters": {
                **{
                    name: registry.counter(
                        _PREFIX + name, help=f"lifetime decode {name}"
                    )
                    for name in _COUNTER_NAMES
                },
                "spec_draft_tokens_total": registry.counter(
                    "zk_spec_draft_tokens_total",
                    help="draft tokens proposed across all speculative "
                    "windows (k per slot per window)",
                ),
                "spec_accepted_tokens_total": registry.counter(
                    "zk_spec_accepted_tokens_total",
                    help="draft tokens the teacher verify accepted "
                    "(longest prefix match; ratio to proposed = "
                    "acceptance rate)",
                ),
                # Disaggregated page-handoff family (docs/DESIGN.md
                # §22): its own zk_transfer_ prefix like zk_spec_ —
                # the transfer spans two engines/roles, not just the
                # decode path. Registered unconditionally (zero-valued
                # under single-mesh serving) so the scrape surface is
                # stable across topologies.
                "transfer_pages_total": registry.counter(
                    "zk_transfer_pages_total",
                    help="KV pages moved prefill->decode across all "
                    "handoffs",
                ),
                "transfer_bytes": registry.counter(
                    "zk_transfer_bytes",
                    help="KV bytes moved prefill->decode (real page "
                    "bytes, padding lanes excluded)",
                ),
                "transfer_handoffs_total": registry.counter(
                    "zk_transfer_handoffs_total",
                    help="completed page handoffs (one per stream "
                    "admitted into a decode slot)",
                ),
                # Chunked-prefill family (docs/DESIGN.md §25):
                # registered unconditionally (zero-valued under
                # monolithic prefill) so the scrape surface is stable
                # across configs, like zk_transfer_.
                "prefill_chunks_total": registry.counter(
                    "zk_prefill_chunks_total",
                    help="prefill chunk lanes dispatched (one per slot "
                    "per chunk; a monolithic prefill counts zero)",
                ),
            },
            "gauges": {
                "active_slots": registry.gauge(
                    _PREFIX + "active_slots",
                    help="sequence slots currently decoding",
                ),
                "slot_occupancy": registry.gauge(
                    _PREFIX + "slot_occupancy",
                    help="active slots / total slots (1.0 = the slot "
                    "array is the bottleneck when the queue is nonempty)",
                ),
                "queue_depth": registry.gauge(
                    _PREFIX + "queue_depth",
                    help="requests waiting for a slot",
                ),
                "kv_pages_in_use": registry.gauge(
                    _PREFIX + "kv_pages_in_use",
                    help="KV pages holding live tokens across active "
                    "slots",
                ),
                "weights_step": registry.gauge(
                    _PREFIX + "serving_weights_step",
                    help="training step whose weights are live (-1 = "
                    "bind-time weights)",
                    initial=-1,
                ),
                "spec_acceptance_rate": registry.gauge(
                    "zk_spec_acceptance_rate",
                    help="lifetime accepted/proposed draft-token "
                    "fraction (-1 = no speculative window yet)",
                    initial=-1,
                ),
                # Paged-KV family (docs/DESIGN.md §20): REAL pool
                # allocator counts, not the host-side length estimate —
                # deliberately outside the zk_decode_ prefix like the
                # zk_spec_ family (the pool is engine state the
                # prefix cache and every slot share).
                "kv_pool_free_pages": registry.gauge(
                    "zk_kv_pool_free_pages",
                    help="free pages in the shared KV page pool (-1 = "
                    "slot layout, no pool)",
                    initial=-1,
                ),
                "prefix_cache_hit_rate": registry.gauge(
                    "zk_prefix_cache_hit_rate",
                    help="lifetime prompt-token fraction served from "
                    "prefix-cache-shared pages (-1 = no lookup yet or "
                    "prefix cache off)",
                    initial=-1,
                ),
            },
            "hist": {
                "transfer_ms": registry.histogram(
                    "zk_transfer_ms",
                    buckets=DEFAULT_MS_BUCKETS,
                    help="one page handoff: export gather + "
                    "device-to-device (or host-bounce) move + import "
                    "scatter",
                ),
                "ttft_ms": registry.histogram(
                    _PREFIX + "ttft_ms",
                    buckets=DEFAULT_MS_BUCKETS,
                    help="submit-to-first-token wall time",
                ),
                "token_ms": registry.histogram(
                    _PREFIX + "token_ms",
                    buckets=DEFAULT_MS_BUCKETS,
                    help="one decode dispatch (one token per active "
                    "slot)",
                ),
                "prefill_ms": registry.histogram(
                    _PREFIX + "prefill_ms",
                    buckets=DEFAULT_MS_BUCKETS,
                    help="one prefill dispatch (KV write + first token)",
                ),
                "spec_accept_length": registry.histogram(
                    "zk_spec_accept_length",
                    buckets=_SPEC_ACCEPT_BUCKETS,
                    help="accepted drafts per verify window per slot "
                    "(0..k; mass at k means raise k, mass at 0 means "
                    "the draft disagrees with the teacher)",
                ),
                "itl_ms": registry.histogram(
                    _PREFIX + "itl_ms",
                    buckets=DEFAULT_MS_BUCKETS,
                    help="inter-token latency: wall time between "
                    "consecutive delivered tokens of one stream — the "
                    "tail a decode-blocking monolithic prefill spikes "
                    "and chunked prefill flattens (docs/DESIGN.md §25)",
                ),
                "prefill_stall_ms": registry.histogram(
                    "zk_prefill_stall_ms",
                    buckets=DEFAULT_MS_BUCKETS,
                    help="per-request admission-to-first-token wall "
                    "time under chunked prefill: the decode-"
                    "interleaving wait a monolithic prefill trades "
                    "for blocked streams (the TTFT-vs-ITL tradeoff's "
                    "other half)",
                ),
            },
            "windows": {},
        }

    @property
    def registry(self) -> MetricsRegistry:
        """The typed instrument registry — attach to an
        ``ObservabilityServer`` to scrape every ``zk_decode_*`` series."""
        return self._obs()["registry"]

    def _series(self, name: str) -> deque:
        return _window_series(self._obs(), name, self.window)

    def _observe(self, name: str, value: float) -> None:
        _observe_sample(self._obs(), name, value, self.window)

    # -- recorders (called by DecodeScheduler) ---------------------------

    def record_ttft(self, ttft_ms: float) -> None:
        """A request's first token landed (prefill emission)."""
        self._observe("ttft_ms", ttft_ms)

    def record_prefill(self, prefill_ms: float, requests: int) -> None:
        obs = self._obs()
        self._observe("prefill_ms", prefill_ms)
        obs["counters"]["prefills_total"].inc()
        obs["counters"]["requests_total"].inc(int(requests))

    def record_decode_step(self, step_ms: float, tokens: int) -> None:
        """One decode dispatch delivered ``tokens`` stream tokens."""
        obs = self._obs()
        self._observe("token_ms", step_ms)
        obs["counters"]["decode_steps_total"].inc()
        obs["counters"]["tokens_total"].inc(int(tokens))

    def record_first_tokens(self, n: int) -> None:
        """Prefill-emitted tokens count toward the stream total too."""
        self._obs()["counters"]["tokens_total"].inc(int(n))

    def record_itl(self, gap_ms: float) -> None:
        """One inter-token gap: wall time between a stream's previous
        delivered token and this one (docs/DESIGN.md §25) — the
        per-stream latency a decode-blocking prefill inflates."""
        self._observe("itl_ms", gap_ms)

    def record_prefill_chunks(
        self, chunks: int, dispatch_ms: float
    ) -> None:
        """One chunked-prefill dispatch served ``chunks`` lanes
        (docs/DESIGN.md §25): each lane is one slot's chunk; the
        dispatch wall time joins the prefill series (a chunk dispatch
        IS a prefill dispatch, just a bounded one)."""
        obs = self._obs()
        obs["counters"]["prefill_chunks_total"].inc(int(chunks))
        obs["counters"]["prefills_total"].inc()
        self._observe("prefill_ms", dispatch_ms)

    def record_prefill_finish(self, requests: int, stall_ms) -> None:
        """``requests`` streams' FINAL chunks landed: they are admitted
        requests now (the monolithic path counts these inside
        ``record_prefill``); each one's admission-to-first-token wall
        time feeds the stall series."""
        obs = self._obs()
        obs["counters"]["requests_total"].inc(int(requests))
        for ms in stall_ms:
            self._observe("prefill_stall_ms", float(ms))

    def record_occupancy(
        self, active: int, slots: int, queue_depth: int, kv_pages: int
    ) -> None:
        gauges = self._obs()["gauges"]
        gauges["active_slots"].set(int(active))
        gauges["slot_occupancy"].set(active / slots if slots else 0.0)
        gauges["queue_depth"].set(int(queue_depth))
        gauges["kv_pages_in_use"].set(int(kv_pages))

    def record_pool(self, free_pages: int, hit_rate: float) -> None:
        """Paged-KV pool vitals (docs/DESIGN.md §20): the allocator's
        real free-page count and the prefix cache's lifetime
        token-level hit rate, refreshed each scheduler iteration with
        the occupancy gauges."""
        gauges = self._obs()["gauges"]
        gauges["kv_pool_free_pages"].set(int(free_pages))
        gauges["prefix_cache_hit_rate"].set(float(hit_rate))

    def record_spec_window(
        self,
        proposed: int,
        accepted: int,
        accept_lengths,
        window_ms: float,
        delivered: int,
    ) -> None:
        """One speculative window committed (docs/DESIGN.md §18):
        ``proposed``/``accepted`` draft tokens across the window's
        slots, per-slot ``accept_lengths`` into the histogram, the
        window wall time into the decode token series (a window IS the
        spec path's decode dispatch unit), and ``delivered`` stream
        tokens into the throughput total."""
        obs = self._obs()
        obs["counters"]["spec_draft_tokens_total"].inc(int(proposed))
        obs["counters"]["spec_accepted_tokens_total"].inc(int(accepted))
        obs["counters"]["tokens_total"].inc(int(delivered))
        obs["counters"]["decode_steps_total"].inc()
        self._observe("token_ms", float(window_ms))
        for a in accept_lengths:
            obs["hist"]["spec_accept_length"].observe(float(a))
        total_p = obs["counters"]["spec_draft_tokens_total"].value
        total_a = obs["counters"]["spec_accepted_tokens_total"].value
        obs["gauges"]["spec_acceptance_rate"].set(
            total_a / total_p if total_p else -1.0
        )

    def record_transfer(
        self, pages: int, nbytes: int, transfer_ms: float
    ) -> None:
        """One completed page handoff (docs/DESIGN.md §22): ``pages``
        real pages / ``nbytes`` real bytes moved prefill->decode, wall
        time into the ``zk_transfer_ms`` histogram + window."""
        obs = self._obs()
        obs["counters"]["transfer_handoffs_total"].inc()
        obs["counters"]["transfer_pages_total"].inc(int(pages))
        obs["counters"]["transfer_bytes"].inc(int(nbytes))
        self._observe("transfer_ms", float(transfer_ms))

    def record_rejected(self) -> None:
        self._obs()["counters"]["rejected_total"].inc()

    def record_deadline_expired(self) -> None:
        self._obs()["counters"]["deadline_expired_total"].inc()

    def record_worker_restart(self) -> None:
        self._obs()["counters"]["worker_restarts_total"].inc()

    def record_weight_swap(self, step: Optional[int] = None) -> None:
        obs = self._obs()
        obs["counters"]["weight_swaps_total"].inc()
        if step is not None:
            obs["gauges"]["weights_step"].set(int(step))

    # -- reduction -------------------------------------------------------

    @property
    def weights_step(self) -> int:
        """The live-weights gauge as a plain int (-1 = bind-time
        weights); stamped onto RequestLog summaries."""
        return int(self._obs()["gauges"]["weights_step"].value)

    @property
    def totals(self) -> Dict[str, int]:
        obs = self._obs()
        return {
            name: int(obs["counters"][name].value)
            for name in (
                _COUNTER_NAMES
                + _CHUNK_COUNTER_NAMES
                + _SPEC_COUNTER_NAMES
                + _TRANSFER_COUNTER_NAMES
            )
        }

    def snapshot(self) -> Dict[str, float]:
        """Flat aggregate of the current windows + totals (absent
        series omitted — an idle engine emits only counters)."""
        windows = self._obs()["windows"]
        out: Dict[str, float] = {
            k: float(v) for k, v in self.totals.items()
        }
        proposed = out.get("spec_draft_tokens_total", 0.0)
        if proposed:
            out["spec_acceptance_rate"] = (
                out["spec_accepted_tokens_total"] / proposed
            )
        for name in (
            "ttft_ms",
            "token_ms",
            "prefill_ms",
            "transfer_ms",
            "itl_ms",
            "prefill_stall_ms",
        ):
            series = windows.get(name)
            if series:
                arr = np.asarray(series)
                out[f"{name[:-3]}_p50_ms"] = float(np.percentile(arr, 50))
                out[f"{name[:-3]}_p99_ms"] = float(np.percentile(arr, 99))
                out[f"{name[:-3]}_mean_ms"] = float(arr.mean())
        return out

    def emit(
        self, writer, step: int = 0, extra: Optional[Mapping[str, float]] = None
    ) -> Dict[str, float]:
        """Write the snapshot through a training-family MetricsWriter
        under the ``decode/`` prefix; returns the snapshot."""
        return _emit_snapshot(self, writer, step, extra, "decode")

    def reset(self) -> None:
        """Zero every series IN PLACE (instrument identity preserved —
        a live ``/metrics`` server keeps rendering; same contract as
        ``ServingMetrics.reset``)."""
        _reset_obs(self)
