"""True paged KV: the shared device page pool, its host-side
allocator, and the radix prefix cache (docs/DESIGN.md §20).

The §15 slot layout provisions every slot's WORST case —
``slots × capacity`` rows of KV HBM — because one slot's rows must be
contiguous. This module is the deferred indirection step (ROADMAP item
4): KV rows live in per-layer POOLS of fixed-size pages
(``[num_pages, page_size, heads, head_dim]``), any slot's logical page
``p`` resolves through a ``[slots, max_pages] int32`` PAGE TABLE
carried as a runtime operand, and three host-side structures make the
pool a serving system rather than a bag of bytes:

- :class:`PagePool` — the allocator: a free-list + per-page refcounts
  over the pool indices, plus the authoritative page table. Admission
  allocates pages for a prompt, each decode/verify dispatch is
  preceded by an ``ensure_rows`` covering its writes, release unrefs —
  a page frees when its LAST reference (active slots + the prefix
  cache) drops. Capacity is pooled: the pool serves any mix of
  lengths summing to ``num_pages × page_size`` resident tokens,
  instead of ``slots`` independent worst cases.
- :class:`RadixPrefixCache` — a radix trie over prompt token prefixes
  at page-chunk granularity. A warm lookup returns the shared pages of
  the longest cached prefix; the requester REFERENCES them instead of
  recomputing prefill for those tokens (TTFT collapses for the
  shared-system-prompt traffic shape). Sharing is copy-on-write at the
  divergence point: the page containing the first divergent position
  is device-copied to a fresh page before the new occupant writes into
  it (full pages strictly before the divergence are never written —
  the validity invariant means writes only land at ``j >= length`` —
  so they share by reference forever). Refcount-0 nodes evict LRU
  under pool pressure.
- int8 quantization hooks — the pool tree optionally stores int8 rows
  plus page-shaped ``[num_pages, page_size, heads]`` float32 scale
  arrays (``ops.quantizers.quantize_kv_rows``), dequantized inside the
  attention read: double the resident tokens per HBM byte.

Validity composes with §15 unchanged: a slot's row ``j`` is meaningful
iff ``j < length``, wherever the page table put it. A freshly-allocated
page may hold a PREVIOUS tenant's rows — the poisoned-free-page
equality tests certify that garbage beyond ``length`` (now: garbage in
recycled pages) cannot perturb output, bit for bit. The prefix cache's
validity argument is determinism: prefill of the same token prefix
under the same weights writes the same bytes, so a cached page IS the
page a cold prefill would have produced — which is why a weight
hot-swap must invalidate the cache (exactly once), and why cached
pages never outlive a swap.

Everything here is HOST state. The device half (the pool tree itself)
is allocated by :func:`allocate_page_pool` and owned/donated by the
``DecodeEngine`` exactly like the slot-layout cache.
"""

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from zookeeper_tpu.serving.decode.prefix_key import walk_insert, walk_match

__all__ = [
    "PagePool",
    "RadixPrefixCache",
    "allocate_page_pool",
    "page_pool_bytes",
]


def allocate_page_pool(
    num_layers: int,
    num_pages: int,
    page_size: int,
    num_heads: int,
    head_dim: int,
    dtype: Any,
    quant: str = "none",
) -> Tuple[dict, ...]:
    """Zero-initialized page-pool pytree: a per-layer tuple of
    ``{"k", "v"}`` pools ``[num_pages, page_size, heads, head_dim]``,
    plus ``{"k_scale", "v_scale"}`` ``[num_pages, page_size, heads]``
    float32 when ``quant="int8"`` (rows stored int8). The engine places
    it under the partitioner's page-pool sharding and donates it
    through every dispatch, exactly like the slot-layout cache."""
    import jax.numpy as jnp

    if num_pages < 1 or page_size < 1:
        raise ValueError(
            f"page pool needs num_pages >= 1 and page_size >= 1, got "
            f"num_pages={num_pages}, page_size={page_size}."
        )
    if quant not in ("none", "int8"):
        raise ValueError(f"quant={quant!r}: expected 'none' or 'int8'.")
    shape = (num_pages, page_size, num_heads, head_dim)
    row_dtype = jnp.int8 if quant == "int8" else dtype
    layers = []
    for _ in range(num_layers):
        layer = {
            "k": jnp.zeros(shape, row_dtype),
            "v": jnp.zeros(shape, row_dtype),
        }
        if quant == "int8":
            # Scale 1.0 everywhere: a zeroed int8 page dequantizes to
            # exact zeros, matching the fp pool's initial state.
            layer["k_scale"] = jnp.ones(shape[:3], jnp.float32)
            layer["v_scale"] = jnp.ones(shape[:3], jnp.float32)
        layers.append(layer)
    return tuple(layers)


def page_pool_bytes(
    num_layers: int,
    num_pages: int,
    page_size: int,
    num_heads: int,
    head_dim: int,
    itemsize: int,
    quant: str = "none",
) -> int:
    """Total HBM the pool occupies (k + v rows, all layers, plus the
    scale arrays when quantized) — the §20 capacity-planning number."""
    rows = 2 * num_layers * num_pages * page_size * num_heads
    total = rows * head_dim * (1 if quant == "int8" else itemsize)
    if quant == "int8":
        total += rows * 4  # float32 scale per (row, head)
    return total


class _TrieNode:
    __slots__ = ("chunk", "page", "children", "parent", "last_used")

    def __init__(self, chunk: Tuple[int, ...], page: int, parent):
        self.chunk = chunk
        self.page = int(page)
        self.children: Dict[Tuple[int, ...], "_TrieNode"] = {}
        self.parent = parent
        self.last_used = 0


class RadixPrefixCache:
    """Radix trie over prompt token prefixes, page-chunk keyed.

    Internal nodes hold one FULL ``page_size`` token chunk each (the
    page covering those positions); a leaf may hold a PARTIAL tail
    chunk. Lookup walks exact full-chunk matches, then takes the
    longest common prefix against any child's chunk for the partial
    tail — a partial hit shares that child's page, which the caller
    must copy-on-write before its first write lands in it.

    The cache holds its OWN reference on every node's page (via the
    ``ref``/``unref`` callables, wired to the :class:`PagePool`
    refcounts), so cached pages survive their inserting slot's release;
    :meth:`evict_lru` drops least-recently-used childless nodes whose
    page the cache alone still references (``refcount == 1`` — the only
    evictions that actually free pool pages). :meth:`clear` is the
    hot-swap invalidation: cached pages hold K/V of the OLD weights and
    must never serve a warm hit under the new ones.
    """

    def __init__(self, page_size: int, ref, unref, evictable) -> None:
        if page_size < 1:
            raise ValueError(f"page_size={page_size} must be >= 1.")
        self.page_size = int(page_size)
        self._ref = ref
        self._unref = unref
        self._evictable = evictable  # page -> bool (refcount == 1)
        self._root = _TrieNode((), -1, None)
        self._clock = 0
        #: Token-level accounting behind ``zk_prefix_cache_hit_rate``.
        self.lookup_tokens = 0
        self.hit_tokens = 0
        self.lookups = 0
        self.hits = 0
        self.evicted_pages = 0
        self.invalidations = 0

    def _touch(self, node: _TrieNode) -> None:
        self._clock += 1
        node.last_used = self._clock

    @property
    def nodes(self) -> int:
        count = 0
        stack = [self._root]
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            count += len(n.children)
        return count

    @property
    def hit_rate(self) -> float:
        """Lifetime shared-token fraction (-1 before any lookup)."""
        if not self.lookup_tokens:
            return -1.0
        return self.hit_tokens / self.lookup_tokens

    def lookup(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached prefix of ``tokens``: returns ``(t, pages)``
        where the first ``t`` tokens are covered by the ``ceil(t /
        page_size)`` cached ``pages`` (the last partial when ``t`` is
        off a page boundary — the caller's CoW case). The caller caps
        ``t`` (never the whole prompt — at least the final token is
        always recomputed so the first-emission logits exist) and takes
        its own references on the pages it adopts. The walk itself is
        the shared ``prefix_key.walk_match`` — the fleet router's
        per-replica :class:`~zookeeper_tpu.serving.decode.prefix_key.\
PrefixIndex` predicts THIS method's match length with the same code."""
        tokens = [int(x) for x in tokens]
        self.lookups += 1
        self.lookup_tokens += len(tokens)
        t, visited = walk_match(self._root, tokens, self.page_size)
        pages: List[int] = []
        for node in visited:
            pages.append(node.page)
            self._touch(node)
        if t:
            self.hits += 1
            self.hit_tokens += t
        return t, pages

    def insert(self, tokens: Sequence[int], pages: Sequence[int]) -> int:
        """Cache ``tokens``' pages (``pages[i]`` covers positions
        ``[i*page_size, (i+1)*page_size)``; the last may be partial).
        Existing nodes keep their ORIGINAL page — by determinism the
        bytes are identical, and swapping would orphan other sharers'
        view of the trie. Returns how many NEW nodes (= new cache page
        references) were created."""
        ps = self.page_size
        tokens = [int(x) for x in tokens]
        created = 0
        visited = walk_insert(
            self._root,
            tokens,
            ps,
            lambda chunk, i, parent: _TrieNode(chunk, pages[i], parent),
            # A partial tail is cached only when a page actually covers
            # those positions.
            tail=len(pages) > len(tokens) // ps,
        )
        for node, was_created in visited:
            if was_created:
                self._ref(node.page)
                created += 1
            self._touch(node)
        return created

    def evict_lru(self, want_pages: int) -> int:
        """Free pool pages by dropping LRU childless nodes whose page
        only the cache still references. Returns pages actually freed
        (may be < ``want_pages`` when everything left is shared with an
        active slot or is an interior node). One DFS collects the whole
        evictable-leaf layer and frees it in LRU order; the outer loop
        rescans only when evictions exposed NEW leaves (parents of
        fully-evicted subtrees) — so the cost is one walk per trie
        LAYER consumed, not one per page (this runs under the
        scheduler lock)."""
        freed = 0
        while freed < want_pages:
            leaves = []
            stack = [self._root]
            while stack:
                n = stack.pop()
                for child in n.children.values():
                    if child.children:
                        stack.append(child)
                    elif self._evictable(child.page):
                        leaves.append(child)
            if not leaves:
                return freed
            leaves.sort(key=lambda n: n.last_used)
            for victim in leaves:
                if freed >= want_pages:
                    return freed
                del victim.parent.children[victim.chunk]
                self._unref(victim.page)
                self.evicted_pages += 1
                freed += 1
        return freed

    def clear(self) -> int:
        """Drop every cached node + reference (the hot-swap
        invalidation). Returns nodes dropped."""
        dropped = 0
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self._unref(n.page)
            dropped += 1
        self._root = _TrieNode((), -1, None)
        # Counted unconditionally: "how many times was the cache
        # invalidated" is the hot-swap-discipline number the chaos
        # tests pin (exactly once per applied swap), not "how many
        # invalidations found nodes to drop".
        self.invalidations += 1
        return dropped


class PagePool:
    """Host-side page allocator + page table for one decode engine's
    shared device pool (see module docstring).

    The DEVICE pool tree is owned by the engine; this object owns the
    indices: the free list, per-page refcounts, the authoritative
    ``[slots, max_pages]`` table the dispatches carry as a runtime
    operand, and (optionally) the radix prefix cache whose nodes hold
    their own page references. NOT thread-safe by itself — the
    scheduler calls every mutator under its own lock, the same
    discipline as its slot arrays.
    """

    def __init__(
        self,
        *,
        num_pages: int,
        page_size: int,
        slots: int,
        max_pages_per_slot: int,
        prefix_cache: bool = True,
    ) -> None:
        if num_pages < max_pages_per_slot:
            raise ValueError(
                f"num_pages={num_pages} below max_pages_per_slot="
                f"{max_pages_per_slot}: one full-capacity sequence "
                "could never be served."
            )
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.slots = int(slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        #: The runtime page-table operand: -1 = unallocated (dispatches
        #: clip it; masked by ``lengths`` per the validity invariant).
        self.table = np.full(
            (self.slots, self.max_pages_per_slot), -1, np.int32
        )
        self.counts = np.zeros(self.slots, np.int32)
        self.refcount = np.zeros(self.num_pages, np.int32)
        self._free: List[int] = list(range(self.num_pages - 1, -1, -1))
        self.cow_pages = 0
        self.exhausted_events = 0
        self.prefix: Optional[RadixPrefixCache] = (
            RadixPrefixCache(
                self.page_size,
                ref=self._ref,
                unref=self._unref,
                evictable=lambda p: int(self.refcount[p]) == 1,
            )
            if prefix_cache
            else None
        )

    # -- refcounting -----------------------------------------------------

    def _ref(self, page: int) -> None:
        self.refcount[page] += 1

    def _unref(self, page: int) -> None:
        self.refcount[page] -= 1
        if self.refcount[page] < 0:
            raise AssertionError(f"page {page} refcount went negative.")
        if self.refcount[page] == 0:
            self._free.append(int(page))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def pages_for(self, tokens: int) -> int:
        return max(0, math.ceil(int(tokens) / self.page_size))

    def _alloc(self, n: int) -> Optional[List[int]]:
        """Pop ``n`` fresh pages, evicting prefix-cache LRU nodes under
        pressure; None (nothing mutated beyond evictions) when the pool
        is genuinely exhausted."""
        if len(self._free) < n and self.prefix is not None:
            self.prefix.evict_lru(n - len(self._free))
        if len(self._free) < n:
            self.exhausted_events += 1
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self.refcount[p] += 1
        return out

    # -- slot lifecycle --------------------------------------------------

    def assign_prompt(self, slot: int, prompt) -> Optional[dict]:
        """Admission: build ``slot``'s page-table row for ``prompt``
        (1-D int tokens), sharing the longest cached prefix when the
        prefix cache is on. Returns a plan dict —

        - ``shared_tokens``: prompt tokens whose KV is already resident
          (prefill is skipped for them; the engine's warm-extend
          program computes only the suffix),
        - ``cow``: ``(src_page, dst_page)`` when the divergence point
          lands mid-page — the engine must device-copy ``src`` into
          ``dst`` BEFORE the suffix dispatch writes into it,

        or None when the pool cannot serve the prompt (caller sheds /
        requeues; nothing was allocated)."""
        if self.counts[slot]:
            raise AssertionError(
                f"slot {slot} still holds pages at admission; release "
                "first."
            )
        prompt = [int(x) for x in np.asarray(prompt).tolist()]
        length = len(prompt)
        shared_tokens = 0
        shared_pages: List[int] = []
        if self.prefix is not None:
            t, pages = self.prefix.lookup(prompt)
            # Never match the WHOLE prompt: the final token is always
            # recomputed so the warm dispatch produces the first
            # emission's logits (and the accounting stays honest).
            t = min(t, length - 1)
            shared_tokens = t
            shared_pages = pages[: self.pages_for(t)]
        n_full_shared = shared_tokens // self.page_size
        partial = shared_tokens % self.page_size != 0
        total_pages = self.pages_for(length)
        fresh_needed = total_pages - n_full_shared
        fresh = self._alloc(fresh_needed)
        if fresh is None:
            return None
        row = list(shared_pages[:n_full_shared]) + fresh
        for p in shared_pages[:n_full_shared]:
            self._ref(p)
        cow = None
        if partial:
            # Divergence mid-page: the suffix writes into this page at
            # offset shared_tokens % page_size, so the shared bytes are
            # copied to the first fresh page (device copy, engine-run).
            cow = (int(shared_pages[n_full_shared]), int(fresh[0]))
            self.cow_pages += 1
        self.table[slot, :len(row)] = row
        self.counts[slot] = len(row)
        return {"shared_tokens": shared_tokens, "cow": cow}

    def adopt_slot(self, slot: int, n_pages: int) -> Optional[List[int]]:
        """Disaggregated handoff, destination side (docs/DESIGN.md
        §22): allocate ``n_pages`` FRESH pages and install them as
        ``slot``'s table row — no prefix lookup, no sharing; the page
        CONTENTS arrive by transfer from another engine's pool.
        Returns the page list (the transfer's scatter targets), or
        None when the pool cannot serve it (nothing mutated beyond
        evictions — caller requeues or sheds). Unwind a failed
        transfer with :meth:`release_slot`."""
        if self.counts[slot]:
            raise AssertionError(
                f"slot {slot} still holds pages at adoption; release "
                "first."
            )
        n_pages = int(n_pages)
        if n_pages < 1 or n_pages > self.max_pages_per_slot:
            raise ValueError(
                f"adopt_slot needs 1..{self.max_pages_per_slot} pages, "
                f"got {n_pages}."
            )
        fresh = self._alloc(n_pages)
        if fresh is None:
            return None
        self.table[slot, :n_pages] = fresh
        self.counts[slot] = n_pages
        return fresh

    def ensure_rows(self, slot: int, rows: int) -> bool:
        """Grow ``slot``'s row to cover ``rows`` total KV rows (the
        pre-dispatch guarantee: decode needs ``length + 1``, a verify
        window ``length + w``). False = pool exhausted after eviction;
        nothing was allocated."""
        needed = self.pages_for(rows)
        if needed > self.max_pages_per_slot:
            raise ValueError(
                f"slot {slot} needs {needed} pages for {rows} rows, "
                f"table holds {self.max_pages_per_slot}."
            )
        have = int(self.counts[slot])
        if needed <= have:
            return True
        fresh = self._alloc(needed - have)
        if fresh is None:
            return False
        self.table[slot, have:needed] = fresh
        self.counts[slot] = needed
        return True

    def release_slot(self, slot: int) -> None:
        """Drop the slot's references (stream finished/failed). Pages
        the prefix cache also references stay resident for warm hits;
        everything else returns to the free list."""
        n = int(self.counts[slot])
        for i in range(n):
            self._unref(int(self.table[slot, i]))
        self.table[slot, :n] = -1
        self.counts[slot] = 0

    def insert_prefix(self, slot: int, prompt) -> int:
        """Cache the slot's prompt pages for future warm hits (called
        after the prefill/extend dispatch landed their contents)."""
        if self.prefix is None:
            return 0
        prompt = np.asarray(prompt)
        n = self.pages_for(int(prompt.shape[0]))
        return self.prefix.insert(
            prompt.tolist(), [int(p) for p in self.table[slot, :n]]
        )

    def invalidate_prefix(self) -> int:
        """Hot-swap invalidation: cached pages hold OLD-weight K/V."""
        if self.prefix is None:
            return 0
        return self.prefix.clear()

    def reset(self) -> None:
        """Return to the freshly-constructed allocation state (the
        engine's ``_reset_cache`` pairing, docs/DESIGN.md §20): table
        cleared, refcounts zeroed, every page free, the prefix trie
        dropped — the device pool it indexed was just reallocated
        zeroed, so every cached node points at bytes that no longer
        exist. Lifetime counters (CoW, evictions, hit accounting)
        survive; the trie's drop counts as an invalidation."""
        self.table.fill(-1)
        self.counts.fill(0)
        self.refcount.fill(0)
        self._free = list(range(self.num_pages - 1, -1, -1))
        if self.prefix is not None:
            old = self.prefix
            fresh = RadixPrefixCache(
                self.page_size,
                ref=self._ref,
                unref=self._unref,
                evictable=lambda p: int(self.refcount[p]) == 1,
            )
            fresh.lookup_tokens = old.lookup_tokens
            fresh.hit_tokens = old.hit_tokens
            fresh.lookups = old.lookups
            fresh.hits = old.hits
            fresh.evicted_pages = old.evicted_pages
            fresh.invalidations = old.invalidations + 1
            self.prefix = fresh

    # -- accounting ------------------------------------------------------

    @property
    def prefix_hit_rate(self) -> float:
        if self.prefix is None:
            return -1.0
        return self.prefix.hit_rate

    def leak_check(self) -> int:
        """Pages absent from the free list that nothing references
        (must be 0 — the chaos tests pin it: a crash path that forgot
        a release would strand pages here forever)."""
        return (
            self.num_pages
            - len(self._free)
            - int(np.sum(self.refcount > 0))
        )

    def status(self) -> dict:
        """The ``/statusz`` ``kv_pool`` sub-section."""
        out = {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "used_pages": self.used_pages,
            "free_pages": self.free_pages,
            "fill": round(self.used_pages / self.num_pages, 4),
            "cow_pages": self.cow_pages,
            "exhausted_events": self.exhausted_events,
            # Stranded pages (must be 0): exposed here so the chaos
            # certification can assert leak-freedom over /statusz on a
            # live worker process, not just in-process.
            "leaked": self.leak_check(),
        }
        if self.prefix is not None:
            out.update(
                prefix_nodes=self.prefix.nodes,
                prefix_lookups=self.prefix.lookups,
                prefix_hits=self.prefix.hits,
                prefix_hit_rate=round(self.prefix.hit_rate, 4),
                prefix_evicted_pages=self.prefix.evicted_pages,
                prefix_invalidations=self.prefix.invalidations,
            )
        return out
