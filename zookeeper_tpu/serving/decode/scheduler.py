"""Slot-refill continuous batching: the host loop over the two decode
programs.

The ``MicroBatcher`` coalesces independent forwards; autoregressive
streams need a different shape — a sequence OCCUPIES device state (its
KV slot) across many dispatches, so the scheduling unit is the SLOT,
not the request. The ``DecodeScheduler`` owns that loop:

1. **Admit**: free slots are refilled from the FIFO queue — a group of
   queued prompts rides one bucketed prefill dispatch, which writes
   their KV pages and emits each request's first token (the TTFT
   emission). A finished sequence's slot is refilled WITHOUT draining
   or recompiling anything: the decode program's shape is the full
   slot array, always.
2. **Decode**: one ``decode_step`` dispatch advances EVERY active slot
   one token; tokens stream into each request's
   :class:`DecodeStream` as they are read back.
3. **Finish**: EOS, per-request ``max_new_tokens``, the engine's
   KV/positional capacity, or a deadline ends a stream and frees its
   slot for the next admit round.

Admission control is the PR 4 machinery re-expressed for streams:
``shed_above`` sheds with :class:`RejectedError` before enqueueing,
per-request deadlines fail with :class:`DeadlineExpiredError` — at
admission planning (never prefilled late) and mid-stream (a stream
never runs past its deadline; ``result()`` never blocks past it) —
and an injected or real crash of the scheduling loop fails every
queued AND in-flight stream cleanly with :class:`WorkerCrashedError`
(``FaultPlan.decode_worker_crash`` drives the leg deterministically),
restarting on the next ``submit()``.

Weight hot-swaps go through :meth:`request_swap`, which upholds the
one-weight-version-per-SEQUENCE contract the dispatch-atomic
``swap_weights`` alone cannot (a stream spans many dispatches): the
swap is deferred, admission pauses so the slot array drains naturally
(bounded by ``max_new_tokens``/deadlines), and the swap applies at the
first empty-slot-array boundary — every in-flight stream finishes
entirely on the weights it started with, every stream admitted after
the swap runs entirely on the new ones. Under speculation the staged
swap is of the TEACHER (the authoritative model): the draft is never
swapped mid-flight — a stale draft only lowers acceptance, never
correctness.

With a bound :class:`SpeculativeDecoding` the decode phase runs the
two-model schedule instead (docs/DESIGN.md §18): per iteration the
draft proposes ``k`` tokens per active slot (one width-2 catch-up
append + ``k - 1`` draft steps), ONE teacher ``decode_verify`` scores
all ``k + 1`` window positions, and greedy acceptance (longest prefix
match, plus the teacher's own token at the first mismatch) commits
1..k+1 tokens per slot — mixed accept lengths across slots are pure
host bookkeeping, no drain, no recompile. Rollback is by-length: a
rejected suffix's cache rows are simply never advanced over. Slots
within a window of their token limit fall back to plain ``decode_step``
iterations (the capacity-truncation contract is the plain path's,
verbatim), and every emitted token remains the teacher's argmax given
the committed prefix — speculative greedy output is certified
bit-identical to plain greedy decode.

Threading mirrors the batcher: ``synchronous=True`` (default) is
thread- and clock-free — the caller drives via ``drain()`` /
``result()`` (deterministic tier-1 mode; deadline tests use
``deadline_ms=0`` = expiry-by-construction); async mode runs the loop
on one ``zk-decode-scheduler`` daemon thread.
"""

import logging
import threading
import time
from collections import deque
from typing import Any, List, Optional

import numpy as np

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.observability import recorder as _recorder
from zookeeper_tpu.observability import trace as _trace
from zookeeper_tpu.observability.requests import RequestLog, next_rid
from zookeeper_tpu.serving.batcher import (
    DeadlineExpiredError,
    RejectedError,
    WorkerCrashedError,
    outcome_of,
)

logger = logging.getLogger(__name__)

__all__ = ["DecodeScheduler", "DecodeStream"]


class DecodeStream:
    """Handle for one generation request: tokens stream in as the
    scheduler produces them; ``result()`` yields the full generated
    array. Iterating the handle yields tokens incrementally (in
    synchronous mode iteration DRIVES the scheduler, like
    ``PendingResult.result`` drives the batcher)."""

    def __init__(
        self,
        scheduler: "DecodeScheduler",
        prompt: np.ndarray,
        max_new_tokens: int,
        deadline_at: Optional[float],
        eos_token: Optional[int],
        rid: Optional[int] = None,
    ) -> None:
        self._scheduler = scheduler
        self.prompt = prompt
        self._max_new = int(max_new_tokens)
        self._deadline_at = deadline_at
        self._eos = eos_token
        self._tokens: List[int] = []
        self._done = False
        self._error: Optional[BaseException] = None
        self._finish_reason: Optional[str] = None
        # Speculative accounting (docs/DESIGN.md §18): drafts proposed /
        # accepted for THIS stream, stamped into its RequestLog detail.
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._t_submit = time.perf_counter()
        #: Submit-to-first-token milliseconds (None until it lands).
        self.ttft_ms: Optional[float] = None
        # Prompt tokens served from the radix prefix cache at admission
        # (stamped by _admit from the page-pool plan; stays 0 for cold
        # admissions and the slot layout).
        self._shared_tokens = 0
        #: Request id minted at submit (docs/DESIGN.md §16); its trace
        #: records render as one Perfetto flow and its terminal summary
        #: lands in the scheduler's RequestLog.
        self.rid = rid
        self._t_dispatch_ns: Optional[int] = None
        self._slot: Optional[int] = None
        # Which serving role last dispatched this stream ("" until the
        # first dispatch): single-mesh scheduling stamps "decode"; the
        # disaggregated scheduler advances it prefill -> transfer ->
        # decode, and the terminal RequestLog summary records where
        # the stream ended (docs/DESIGN.md §22).
        self._role: str = ""
        # Completion races between the worker (finish), a crash handler
        # (fail) and the caller's deadline expiry: first wins.
        self._cond = threading.Condition()

    # -- state -----------------------------------------------------------

    @property
    def done(self) -> bool:
        return self._done

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    @property
    def finish_reason(self) -> Optional[str]:
        """"eos" / "length" (max_new_tokens) / "capacity" (KV or
        positional limit) — None while streaming or on failure."""
        return self._finish_reason

    @property
    def shared_tokens(self) -> int:
        """Prompt tokens whose KV came warm from the radix prefix
        cache at admission (0 = cold admission or slot layout) — the
        per-request observability hook behind the fleet router's
        affinity certification (docs/DESIGN.md §23)."""
        return self._shared_tokens

    @property
    def tokens_so_far(self) -> np.ndarray:
        """Generated tokens delivered so far (valid even for a stream
        that later failed on deadline/crash — partial output is real
        output)."""
        with self._cond:
            return np.asarray(self._tokens, np.int32)

    def expired(self, now: Optional[float] = None) -> bool:
        if self._deadline_at is None:
            return False
        return (
            time.perf_counter() if now is None else now
        ) >= self._deadline_at

    # -- scheduler-side transitions --------------------------------------

    def _deliver(self, token: int) -> None:
        with self._cond:
            if self._done:
                return
            self._tokens.append(int(token))
            self._cond.notify_all()

    def _finish(self, reason: str) -> None:
        with self._cond:
            if self._done:
                return
            self._done = True
            self._finish_reason = reason
            self._cond.notify_all()
        # Outside the cond (first-transition-wins above guarantees
        # exactly one terminal record per stream). Streams that rode
        # the speculative schedule carry accepted/proposed in their
        # terminal summary (docs/DESIGN.md §18).
        detail = reason
        if self._spec_proposed:
            detail = (
                f"{reason} spec={self._spec_accepted}/{self._spec_proposed}"
            )
        self._scheduler._log_terminal(self, "ok", detail=detail)

    def _fail(self, error: BaseException) -> bool:
        with self._cond:
            if self._done:
                return False
            self._done = True
            self._error = error
            self._cond.notify_all()
        self._scheduler._log_terminal(
            self, outcome_of(error), detail=type(error).__name__
        )
        return True

    def _expire(self) -> bool:
        waited_ms = (time.perf_counter() - self._t_submit) * 1e3
        return self._fail(
            DeadlineExpiredError(
                f"generation deadline expired after {waited_ms:.1f}ms "
                f"({len(self._tokens)} of {self._max_new} tokens "
                "generated; partial output in tokens_so_far)"
            )
        )

    # -- caller side -----------------------------------------------------

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """The full generated token array. Synchronous mode drives the
        scheduler to completion; async mode blocks — but NEVER past the
        request's deadline (on expiry the stream fails with
        :class:`DeadlineExpiredError` even if the worker is stalled)."""
        if not self._done:
            self._scheduler._drive(self, timeout)
        if self._error is not None:
            raise self._error
        return np.asarray(self._tokens, np.int32)

    def __iter__(self):
        """Incremental token stream (generated tokens, in order)."""
        served = 0
        while True:
            with self._cond:
                available = len(self._tokens)
            while served < available:
                yield self._tokens[served]
                served += 1
            if self._done:
                if self._error is not None:
                    raise self._error
                with self._cond:
                    remaining = self._tokens[served:]
                yield from remaining
                return
            self._scheduler._advance(self)


@component
class DecodeScheduler:
    """Continuous-batching scheduler over a
    :class:`~zookeeper_tpu.serving.decode.engine.DecodeEngine` (see
    module docstring)."""

    #: Default generation budget per request (``submit`` overrides).
    max_new_tokens: int = Field(32)
    #: Default per-request deadline in ms (0 = none); ``submit``'s
    #: ``deadline_ms`` overrides. Expired requests fail with
    #: :class:`DeadlineExpiredError` — queued, mid-stream, and in
    #: ``result()`` (which never blocks past it).
    default_deadline_ms: float = Field(0.0)
    #: Load-shedding threshold in QUEUED REQUESTS (0 = off): a submit
    #: that would grow the wait queue past this raises
    #: :class:`RejectedError` instead of queueing — overload fails
    #: fast. An empty queue always admits one request.
    shed_above: int = Field(0)
    #: Backpressure bound on the wait queue (requests): synchronous
    #: mode drains the backlog inline, async mode blocks the submitter.
    max_queue: int = Field(4096)
    #: End-of-sequence token id (-1 = none); ``submit`` overrides.
    #: Generation stops WITH the EOS token delivered.
    eos_token: int = Field(-1)
    #: Thread- and clock-free deterministic mode (tier-1 default):
    #: the caller drives via drain()/result(). False = one
    #: ``zk-decode-scheduler`` daemon thread runs the loop.
    synchronous: bool = Field(True)
    #: Per-iteration token budget for the chunked-prefill planner
    #: (docs/DESIGN.md §25; active only when the engine's
    #: ``prefill_chunk_tokens`` is on): each iteration spends the
    #: budget FIRST on every active decode slot (one token each; a
    #: speculative window counts k + 1), then on pending prefill
    #: chunks — decode never waits behind a prompt. 0 (default) sizes
    #: it automatically to ``slots × window + prefill_chunk_tokens``
    #: (full decode occupancy plus one whole chunk per iteration). A
    #: smaller explicit budget squeezes prefill harder under decode
    #: load, down to a 1-token/iteration progress floor.
    token_budget: int = Field(0)

    # -- wiring ----------------------------------------------------------

    def bind(
        self,
        engine,
        metrics=None,
        request_log=None,
        speculative=None,
        guard=None,
    ) -> "DecodeScheduler":
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens={self.max_new_tokens} must be >= 1 "
                "(prefill always emits one token)."
            )
        if self.shed_above < 0 or self.default_deadline_ms < 0:
            raise ValueError(
                f"shed_above={self.shed_above} and default_deadline_ms="
                f"{self.default_deadline_ms} must be >= 0 (0 disables)."
            )
        if self.max_queue < 1:
            raise ValueError(f"max_queue={self.max_queue} must be >= 1.")
        if self.token_budget < 0:
            raise ValueError(
                f"token_budget={self.token_budget} must be >= 0 "
                "(0 sizes the chunked-prefill budget automatically)."
            )
        engine._require_bound()
        object.__setattr__(self, "_engine", engine)
        object.__setattr__(self, "_metrics", metrics)
        # Per-service terminal-request ring (docs/DESIGN.md §16).
        object.__setattr__(
            self,
            "_request_log",
            request_log if request_log is not None else RequestLog("decode"),
        )
        if speculative is not None:
            speculative._require_bound()
            if speculative.engine is not engine:
                raise ValueError(
                    "speculative binding mirrors a different teacher "
                    "engine; bind the scheduler and the speculative "
                    "config to the SAME DecodeEngine."
                )
        object.__setattr__(self, "_speculative", speculative)
        # Optional OverloadGuard (docs/DESIGN.md §24): predicted-miss
        # admission + brown-out. _brownout_active is the scheduler's
        # APPLIED state — it only tracks guard.brownout_engaged at the
        # drain boundary (_maybe_apply_brownout), never mid-batch.
        object.__setattr__(self, "_guard", guard)
        object.__setattr__(self, "_brownout_active", False)
        n = int(engine.slots)
        object.__setattr__(self, "_queue", deque())
        object.__setattr__(self, "_slot_stream", [None] * n)
        object.__setattr__(self, "_slot_lengths", np.zeros(n, np.int64))
        object.__setattr__(self, "_slot_tokens", np.zeros(n, np.int32))
        # Draft-cache bookkeeping (speculative schedule): valid draft
        # KV rows per slot, plus the <=1 committed token the teacher
        # has cached but the draft has not yet consumed (the full-
        # acceptance catch-up — docs/DESIGN.md §18).
        object.__setattr__(self, "_draft_lengths", np.zeros(n, np.int64))
        object.__setattr__(self, "_draft_pending", [[] for _ in range(n)])
        # Chunked prefill (docs/DESIGN.md §25): slot -> {"pos": next
        # uncommitted prompt offset, "admit_t": perf_counter at
        # admission} while a prompt is mid-prefill. A slot in
        # _chunk_state owns pages + a stream but must NOT decode —
        # its KV prefix is still being appended chunk by chunk.
        chunked = bool(engine.paged) and int(engine.prefill_chunk_tokens) > 0
        object.__setattr__(self, "_chunked", chunked)
        object.__setattr__(self, "_chunk_state", {})
        # Wall-clock of each slot's most recent token delivery, for
        # the inter-token-latency histogram; 0 = no token emitted yet
        # for the current occupant.
        object.__setattr__(self, "_slot_last_emit", np.zeros(n, np.float64))
        object.__setattr__(self, "_lock", threading.RLock())
        # Serializes scheduler ITERATIONS (plan -> dispatch -> commit)
        # so ``_lock`` can be released across the device dispatches:
        # submit()/status() only ever wait on bookkeeping, never on a
        # prefill/decode wall time (the MicroBatcher dispatch-outside-
        # the-lock discipline).
        object.__setattr__(self, "_step_lock", threading.Lock())
        object.__setattr__(self, "_cv", threading.Condition())
        object.__setattr__(self, "_worker", None)
        object.__setattr__(self, "_stop", threading.Event())
        object.__setattr__(self, "_swap_pending", None)
        return self

    def _require_bound(self) -> None:
        if getattr(self, "_engine", None) is None:
            raise RuntimeError(
                "DecodeScheduler is not bound: call "
                "scheduler.bind(engine) before submit()."
            )

    @property
    def request_log(self) -> Optional[RequestLog]:
        """This scheduler's terminal-request ring (None before bind)."""
        return getattr(self, "_request_log", None)

    def _log_terminal(
        self, stream: "DecodeStream", outcome: str, detail: Optional[str]
    ) -> None:
        """One compact RequestLog summary per TERMINAL stream (called
        by the stream's first-wins finish/fail transition)."""
        log = getattr(self, "_request_log", None)
        if log is None or stream.rid is None:
            return
        if outcome != "ok" and _trace.enabled():
            # The ok path already marked its terminal record
            # (decode_stream_finish, rid-tagged); failed streams get
            # theirs here so every outcome's flow chain has a terminus.
            _trace.event(
                "decode_stream_fail",
                rid=stream.rid,
                attrs={"outcome": outcome, "detail": detail},
            )
        complete_ns = time.perf_counter_ns()
        log.append(
            stream.rid,
            outcome,
            enqueue_ns=int(stream._t_submit * 1e9),
            dispatch_ns=stream._t_dispatch_ns,
            complete_ns=complete_ns,
            tokens=len(stream._tokens),
            slot=stream._slot,
            weights_step=(
                self._metrics.weights_step
                if self._metrics is not None
                else None
            ),
            detail=detail,
            role=stream._role or None,
        )
        guard = getattr(self, "_guard", None)
        if (
            guard is not None
            and guard.enabled
            and outcome == "ok"
            and stream._t_dispatch_ns is not None
        ):
            # Feed the admission estimator from observed successes:
            # service = dispatch→complete per generated token, wait =
            # submit→dispatch. Failures are excluded — their timings
            # describe the failure mode, not the service rate.
            dispatch_ns = stream._t_dispatch_ns
            guard.observe_service(
                (complete_ns - dispatch_ns) / 1e6,
                max(1, len(stream._tokens)),
            )
            guard.observe_wait(
                (dispatch_ns - stream._t_submit * 1e9) / 1e6
            )

    # -- submission ------------------------------------------------------

    def _deadline_at(self, deadline_ms: Optional[float]) -> Optional[float]:
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms or None
        if deadline_ms is None:
            return None
        if deadline_ms < 0:
            raise ValueError(f"deadline_ms={deadline_ms} must be >= 0.")
        return time.perf_counter() + deadline_ms / 1e3

    def submit(
        self,
        prompt: Any,
        *,
        max_new_tokens: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        eos_token: Optional[int] = None,
        rid: Optional[int] = None,
    ) -> DecodeStream:
        """Enqueue one prompt (1-D int tokens); returns a
        :class:`DecodeStream`. ``deadline_ms=None`` falls back to the
        component default (0 = none) while an EXPLICIT ``0`` is
        already-expired (the deterministic clock-free chaos idiom).
        Raises :class:`RejectedError` without enqueueing past the shed
        threshold. ``rid`` adopts an EXTERNALLY-minted request id —
        the fleet router propagates its own so one request is
        traceable router → worker across process boundaries
        (docs/DESIGN.md §23); None mints locally as before."""
        self._require_bound()
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError(
                f"prompt must be a non-empty 1-D int token array, got "
                f"shape {prompt.shape}."
            )
        engine = self._engine
        if prompt.shape[0] > engine.max_prompt:
            raise ValueError(
                f"prompt of {prompt.shape[0]} tokens exceeds the "
                f"largest seq bucket {engine.max_prompt}; widen "
                "engine.seq_buckets."
            )
        if prompt.shape[0] >= engine.token_limit:
            # token_limit is the hard TOTAL (prompt + generated); a
            # prompt at or past it leaves no room to emit even the
            # first token within the truncate-at-EXACTLY-token_limit
            # contract (docs/DESIGN.md §15).
            raise ValueError(
                f"prompt of {prompt.shape[0]} tokens leaves no room to "
                f"generate within token_limit={engine.token_limit} "
                f"(min of KV capacity {engine.capacity} and positional "
                f"table {engine.position_cap}); shorten the prompt or "
                "raise kv_capacity / the model's max_seq_len."
            )
        new = int(
            max_new_tokens if max_new_tokens is not None
            else self.max_new_tokens
        )
        if new < 1:
            raise ValueError(f"max_new_tokens={new} must be >= 1.")
        eos = eos_token if eos_token is not None else (
            int(self.eos_token) if int(self.eos_token) >= 0 else None
        )
        # Minted before admission control, so shed streams are
        # traceable and RequestLog-recorded too (docs/DESIGN.md §16);
        # a router-minted rid is adopted instead (docs/DESIGN.md §23).
        rid = next_rid() if rid is None else int(rid)
        stream = DecodeStream(
            self,
            prompt,
            new,
            self._deadline_at(deadline_ms),
            eos,
            rid=rid,
        )
        with self._lock:
            if (
                self.shed_above > 0
                and self._queue
                and len(self._queue) + 1 > self.shed_above
            ):
                if self._metrics is not None:
                    self._metrics.record_rejected()
                if _trace.enabled():
                    _trace.event(
                        "decode_request_shed",
                        rid=rid,
                        attrs={"queue_depth": len(self._queue)},
                    )
                self._log_terminal(stream, "shed", detail="RejectedError")
                raise RejectedError(
                    f"decode queue at {len(self._queue)} requests; "
                    f"admitting one more would exceed shed_above="
                    f"{self.shed_above} — request shed (service "
                    "overloaded, retry with backoff)."
                )
            self._guard_check(stream, new)
            backpressure = len(self._queue) + 1 > self.max_queue
            if not backpressure:
                self._queue.append(stream)
                if _trace.enabled():
                    _trace.event(
                        "decode_request_enqueue",
                        rid=rid,
                        attrs={
                            "prompt_tokens": int(prompt.shape[0]),
                            "queue_depth": len(self._queue),
                        },
                    )
        if backpressure:
            if self.synchronous:
                self.drain()  # serve the backlog inline, then queue
                with self._lock:
                    self._queue.append(stream)
            else:
                while True:
                    with self._lock:
                        if len(self._queue) + 1 <= self.max_queue:
                            self._queue.append(stream)
                            break
                    if self._stop.is_set():
                        raise RuntimeError(
                            "DecodeScheduler closed while submit was "
                            "blocked on backpressure."
                        )
                    # Bounded cv wait, not a busy-poll: the scheduler
                    # notifies per iteration; the timeout re-checks
                    # _stop/worker death (no lost-wakeup hang).
                    with self._cv:
                        self._cv.wait(0.01)
        if not self.synchronous:
            self._ensure_worker()
            with self._cv:
                self._cv.notify_all()
        return stream

    def _guard_check(self, stream: DecodeStream, new: int) -> None:
        """Predicted-miss admission (docs/DESIGN.md §24): shed at
        submit when the guard's EWMA completion estimate says this
        stream cannot meet its deadline behind the CURRENT queue.
        Queued work is measured in tokens-still-owed (each queued
        stream's max_new budget), the unit the per-token service EWMA
        speaks. With chunked prefill on, each stream additionally
        owes its REMAINING prefill chunks — one budget unit per chunk
        dispatch, work the iteration planner schedules exactly like a
        decode token (docs/DESIGN.md §25). Monolithic prefill keeps
        the historical prefill-is-free posture. Caller holds the
        lock; same empty-queue invariant as the static check."""
        guard = getattr(self, "_guard", None)
        if guard is None or not guard.enabled:
            return
        from zookeeper_tpu.serving.guardrails import PredictedMissError

        deadline_ms = (
            (stream._deadline_at - time.perf_counter()) * 1e3
            if stream._deadline_at is not None
            else None
        )
        queued_tokens = sum(
            s._max_new + self._chunk_units(int(s.prompt.shape[0]))
            for s in self._queue
        )
        if getattr(self, "_chunked", False):
            # Mid-prefill slots still owe their uncommitted chunks.
            for slot, st in self._chunk_state.items():
                s = self._slot_stream[slot]
                if s is None:
                    continue
                queued_tokens += self._chunk_units(
                    int(s.prompt.shape[0]) - int(st["pos"])
                )
        ok, predicted = guard.admit(
            queued_units=queued_tokens,
            request_units=new + self._chunk_units(int(stream.prompt.shape[0])),
            deadline_ms=deadline_ms,
        )
        if ok:
            return
        if self._metrics is not None:
            self._metrics.record_rejected()
        if _trace.enabled():
            _trace.event(
                "decode_request_shed",
                rid=stream.rid,
                attrs={
                    "queue_depth": len(self._queue),
                    "reason": "predicted_miss",
                    "predicted_ms": round(predicted, 3),
                },
            )
        self._log_terminal(
            stream,
            "shed",
            detail=f"PredictedMissError predicted_ms={predicted:.1f}",
        )
        raise PredictedMissError(
            f"predicted completion in {predicted:.1f}ms exceeds the "
            f"{deadline_ms:.1f}ms deadline with {queued_tokens} tokens "
            "queued ahead — shed at admission rather than served late."
        )

    def _chunk_units(self, prompt_tokens: int) -> int:
        """Remaining-prefill work in admission-budget units: the
        number of chunk dispatches still owed for ``prompt_tokens``
        uncommitted prompt tokens (ceil-divide by the chunk size).
        0 when chunking is off — monolithic prefill keeps the
        historical prefill-is-free estimator posture so existing
        deployments see identical admission decisions."""
        if not getattr(self, "_chunked", False) or prompt_tokens <= 0:
            return 0
        cap = int(self._engine.prefill_chunk_tokens)
        return -(-int(prompt_tokens) // cap)

    def generate(self, prompt: Any, **kwargs) -> np.ndarray:
        """Submit + block for the full generation — the one-call API
        (``tokens = scheduler.generate(prompt, max_new_tokens=64)``)."""
        return self.submit(prompt, **kwargs).result()

    # -- weight hot-swap -------------------------------------------------

    def request_swap(
        self, params: Any, model_state: Any = None, *, step: Optional[int] = None
    ) -> None:
        """Stage a weight hot-swap that preserves the one-weight-
        version-per-sequence contract: validation runs HERE (config
        bugs surface at the call site), admission pauses, in-flight
        streams finish on the weights they started with, and the swap
        applies at the first empty-slot-array boundary — zero
        recompiles. A second request before the first applies REPLACES
        it (newest wins, like the async checkpointer's supersede)."""
        self._require_bound()
        self._engine.check_swap(params, model_state)
        with self._lock:
            object.__setattr__(
                self, "_swap_pending", (params, model_state, step)
            )
        if not self.synchronous:
            self._ensure_worker()
            with self._cv:
                self._cv.notify_all()

    @property
    def swap_pending(self) -> bool:
        return getattr(self, "_swap_pending", None) is not None

    def _maybe_apply_swap(self) -> None:
        pending = getattr(self, "_swap_pending", None)
        if pending is None:
            return
        if any(s is not None for s in self._slot_stream):
            return  # in-flight sequences keep their weight version
        params, model_state, step = pending
        self._engine.swap_weights(params, model_state)
        # Paged layout: cached prefix pages hold K/V computed under the
        # OLD weights — a warm hit after the swap would splice stale
        # state into a new-weights stream. Invalidated here, EXACTLY
        # once per applied swap (the staged-swap boundary is the only
        # place weights change under a bound scheduler).
        dropped = self._engine.invalidate_prefix_cache()
        object.__setattr__(self, "_swap_pending", None)
        _trace.event(
            "decode_weight_swap",
            step=step,
            attrs={"deferred": True, "prefix_nodes_dropped": dropped},
        )
        if self._metrics is not None:
            self._metrics.record_weight_swap(step)
        logger.info(
            "decode weights hot-swapped%s (slot array drained, no "
            "recompile)",
            f" to training step {step}" if step is not None else "",
        )

    def _maybe_apply_brownout(self) -> None:
        """Track the guard's brown-out intent at the SAME safe boundary
        as a staged weight swap: the state flips only when the slot
        array is empty, so no in-flight stream ever sees its token
        budget rewritten or its speculation config change mid-sequence
        (docs/DESIGN.md §24). Loudly logged both ways; auto-recovering
        — the guard disengages on its own once admissions stop
        predicting misses. Caller holds ``_lock``."""
        guard = getattr(self, "_guard", None)
        if guard is None or not guard.enabled:
            return
        want = bool(guard.brownout_engaged)
        if want == self._brownout_active:
            return
        if any(s is not None for s in self._slot_stream):
            return  # in-flight sequences finish under the old posture
        object.__setattr__(self, "_brownout_active", want)
        guard.record_brownout_applied(want)
        _trace.event(
            "decode_brownout",
            attrs={
                "engaged": want,
                "max_new_tokens_cap": int(guard.brownout_max_new_tokens),
            },
        )
        if want:
            logger.warning(
                "BROWN-OUT ENGAGED: decode degrading — max_new_tokens "
                "capped at %d, speculation disabled for newly admitted "
                "streams (sustained predicted-miss pressure; "
                "auto-recovers when admissions stop shedding).",
                int(guard.brownout_max_new_tokens),
            )
        else:
            logger.warning(
                "brown-out released: decode back to full token budgets "
                "and speculation."
            )

    # -- the scheduling loop ---------------------------------------------

    def _has_work(self) -> bool:
        with self._lock:
            return bool(self._queue) or any(
                s is not None for s in self._slot_stream
            )

    def _free_slot(self, slot: int) -> None:
        self._slot_stream[slot] = None
        self._chunk_state.pop(slot, None)
        # Paged layout: drop the slot's page references (prefix-cache-
        # shared pages stay resident); slot layout: no-op. Every slot
        # retirement path funnels here so pages can never leak.
        self._engine.release_slot(slot)

    def _finish_or_continue(self, slot: int, token: int) -> None:
        """Deliver ``token`` to the slot's stream and retire the slot
        when the stream is complete. Caller holds the lock."""
        stream = self._slot_stream[slot]
        now = time.perf_counter()
        last = float(self._slot_last_emit[slot])
        if last > 0.0 and self._metrics is not None:
            # Inter-token gap as the CLIENT sees it: previous delivery
            # to this one. Speculative windows deliver their accepted
            # run back-to-back (near-zero gaps) — accurate, the tokens
            # really do arrive together.
            self._metrics.record_itl((now - last) * 1e3)
        self._slot_last_emit[slot] = now
        stream._deliver(token)
        reason = None
        if stream._eos is not None and token == stream._eos:
            reason = "eos"
        elif len(stream._tokens) >= stream._max_new:
            reason = "length"
        elif self._slot_lengths[slot] + 1 >= self._engine.token_limit:
            # The sequence now totals token_limit tokens (cached
            # lengths + the token just delivered): feeding the delivered
            # token back would write past the KV capacity or the
            # positional table. Truncate at EXACTLY token_limit, so
            # every delivered token is full-context-oracle-verifiable.
            reason = "capacity"
        if reason is not None:
            stream._finish(reason)
            self._free_slot(slot)
            if _trace.enabled():
                _trace.event(
                    "decode_stream_finish",
                    rid=stream.rid,
                    attrs={
                        "slot": slot,
                        "reason": reason,
                        "tokens": len(stream._tokens),
                    },
                )

    def _expire_queued(self) -> None:
        now = time.perf_counter()
        if not any(s.expired(now) for s in self._queue):
            return
        kept = deque()
        for stream in self._queue:
            if stream.expired(now):
                if stream._expire() and self._metrics is not None:
                    self._metrics.record_deadline_expired()
            else:
                kept.append(stream)
        object.__setattr__(self, "_queue", kept)

    def _expire_active(self) -> None:
        now = time.perf_counter()
        for slot, stream in enumerate(self._slot_stream):
            if stream is not None and stream.expired(now):
                if stream._expire() and self._metrics is not None:
                    self._metrics.record_deadline_expired()
                self._free_slot(slot)

    def _admit(self) -> None:
        """Refill free slots from the queue head: one bucketed prefill
        dispatch per admitted group. Paused while a weight swap is
        pending (the drain that makes the swap safe). Caller holds
        ``_step_lock``; ``_lock`` is taken per phase so the prefill
        dispatch itself runs unlocked — admitted streams are RESERVED
        into the slot array first, so ``close()``/``_on_crash`` see
        (and can fail) them mid-dispatch."""
        engine = self._engine
        while True:
            with self._lock:
                if self._swap_pending is not None or not self._queue:
                    return
                free = [
                    i for i, s in enumerate(self._slot_stream) if s is None
                ]
                if not free:
                    return
                group: List[DecodeStream] = []
                slots: List[int] = []
                cap = min(len(free), max(engine._prefill_buckets))
                while self._queue and len(group) < cap:
                    stream = self._queue.popleft()
                    if stream.expired():
                        if stream._expire() and self._metrics is not None:
                            self._metrics.record_deadline_expired()
                        continue
                    if self._brownout_active:
                        # Brown-out: every stream admitted while
                        # engaged gets a capped token budget. Applied
                        # at ADMISSION only — in-flight budgets are
                        # never rewritten (docs/DESIGN.md §24).
                        stream._max_new = min(
                            stream._max_new,
                            int(self._guard.brownout_max_new_tokens),
                        )
                    group.append(stream)
                    slots.append(free[len(group) - 1])
                if not group:
                    continue
                t0_ns = time.perf_counter_ns()
                for stream, slot in zip(group, slots):
                    self._slot_stream[slot] = stream
                    self._slot_lengths[slot] = int(stream.prompt.shape[0])
                    self._slot_last_emit[slot] = 0.0
                    # Dispatch attribution BEFORE the device work (a
                    # crash mid-prefill still shows the stream reached
                    # dispatch), rid-tagged so the exporter links the
                    # submit event to this slot's prefill.
                    stream._slot = slot
                    stream._role = "decode"
                    if stream._t_dispatch_ns is None:
                        stream._t_dispatch_ns = t0_ns
                    if _trace.enabled() and stream.rid is not None:
                        _trace.event(
                            "decode_request_dispatch",
                            rid=stream.rid,
                            attrs={"slot": slot},
                        )
            # Page allocation per admitted stream (docs/DESIGN.md §20;
            # slot layout: trivial cold plans). The POOL bookkeeping
            # runs under _lock (close()/crash release pages under the
            # same lock — the PagePool is lock-guarded scheduler
            # state); only the rare one-page CoW copy dispatches
            # outside, like the prefill itself. A pool-exhausted
            # stream is put back at the QUEUE HEAD (its slot
            # reservation undone) — it admits as soon as finishing
            # streams release pages; if the pool cannot serve it even
            # with every slot idle and the prefix cache evicted, it is
            # shed with RejectedError (it could never run).
            plans = []
            admitted: List[DecodeStream] = []
            admitted_slots: List[int] = []
            with self._lock:
                overflow = []
                for stream, slot in zip(group, slots):
                    if self._slot_stream[slot] is not stream:
                        continue  # failed by close()/crash already
                    plan = engine.admit_slot(
                        slot, stream.prompt, copy=False
                    )
                    if plan is None:
                        overflow.append((stream, slot))
                    else:
                        stream._shared_tokens = int(
                            plan.get("shared_tokens") or 0
                        )
                        plans.append(plan)
                        admitted.append(stream)
                        admitted_slots.append(slot)
                others_active = any(
                    s is not None
                    and i not in [sl for _, sl in overflow]
                    for i, s in enumerate(self._slot_stream)
                ) or bool(admitted)
                for stream, slot in reversed(overflow):
                    self._slot_stream[slot] = None
                    if others_active:
                        # Pages free as streams finish: requeue.
                        self._queue.appendleft(stream)
                    else:
                        # Nothing in flight and the pool still cannot
                        # hold this prompt: unservable.
                        if self._metrics is not None:
                            self._metrics.record_rejected()
                        stream._fail(RejectedError(
                            "KV page pool exhausted with no active "
                            "streams to wait for: the prompt needs "
                            "more pages than pool_pages can ever free "
                            "— raise engine.pool_pages or shorten the "
                            "prompt."
                        ))
            if not admitted:
                if overflow:
                    return
                continue
            group, slots = admitted, admitted_slots
            # CoW copies outside the lock (device work). A page whose
            # stream was failed mid-loop just writes bytes into a
            # released page — unreferenced, overwritten or masked by
            # any future tenant (the validity invariant).
            for plan in plans:
                cow = plan.pop("cow", None)
                if cow is not None:
                    engine.copy_page(*cow)
            if getattr(self, "_chunked", False):
                # Chunked admission (docs/DESIGN.md §25): pages are
                # allocated and any warm prefix is already committed
                # (CoW done above), but NO prefill dispatches here —
                # the token-budget planner (_prefill_chunks) appends
                # the prompt chunk by chunk, interleaved with decode
                # iterations, and TTFT is stamped on the FINAL chunk.
                # Warm hits start their cursor past the cached prefix,
                # so fully-warm prompts cost a single 1-token chunk.
                with self._lock:
                    now = time.perf_counter()
                    for stream, slot, plan in zip(group, slots, plans):
                        if self._slot_stream[slot] is not stream:
                            continue  # failed by close()/crash already
                        shared = int(plan.get("shared_tokens") or 0)
                        # While mid-prefill, _slot_lengths tracks the
                        # COMMITTED prefix (the chunk cursor), not the
                        # final prompt length.
                        self._slot_lengths[slot] = shared
                        self._chunk_state[slot] = {
                            "pos": shared,
                            "admit_t": now,
                        }
                continue
            cold = [
                i for i, p in enumerate(plans)
                if not p.get("shared_tokens")
            ]
            warm = [
                i for i, p in enumerate(plans) if p.get("shared_tokens")
            ]
            t0 = time.perf_counter()
            first = np.zeros(len(group), np.int32)
            if cold:
                out = engine.prefill(
                    [group[i].prompt for i in cold],
                    [slots[i] for i in cold],
                )
                for i, tok in zip(cold, out):
                    first[i] = tok
            if warm:
                # Warm-prefix admission: only the suffixes ride the
                # device (the shared pages are already resident) —
                # the TTFT collapse the prefix cache exists for.
                out = engine.prefill_warm(
                    [group[i].prompt for i in warm],
                    [slots[i] for i in warm],
                    [int(plans[i]["shared_tokens"]) for i in warm],
                )
                for i, tok in zip(warm, out):
                    first[i] = tok
            spec = getattr(self, "_speculative", None)
            if spec is not None:
                # Seed the DRAFT cache for the same group/slots (its
                # first-token output is discarded — the teacher's is
                # authoritative and already delivered). One extra
                # dispatch per admission, amortized over the stream.
                # Always the cold prefill: the draft keeps its own
                # slot-layout cache (never prefix-shared — pooling a
                # private, correctness-irrelevant cache buys nothing).
                spec.draft_engine.prefill(
                    [s.prompt for s in group], slots
                )
            dt_ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                now = time.perf_counter()
                delivered = 0
                for stream, slot, token in zip(group, slots, first):
                    if self._slot_stream[slot] is not stream:
                        continue  # failed by close()/crash mid-dispatch
                    stream.ttft_ms = (now - stream._t_submit) * 1e3
                    if self._metrics is not None:
                        self._metrics.record_ttft(stream.ttft_ms)
                    if spec is not None:
                        # Both caches hold exactly the prompt now.
                        self._draft_lengths[slot] = int(
                            stream.prompt.shape[0]
                        )
                        self._draft_pending[slot] = []
                    # Cache the prompt's pages for future warm hits
                    # while the slot still references them.
                    engine.insert_prefix(slot, stream.prompt)
                    self._slot_tokens[slot] = int(token)
                    self._finish_or_continue(slot, int(token))
                    delivered += 1
                if self._metrics is not None:
                    # Count tokens/requests actually DELIVERED (a
                    # stream failed mid-dispatch got no token) — the
                    # dispatch itself still counts once.
                    self._metrics.record_prefill(dt_ms, delivered)
                    self._metrics.record_first_tokens(delivered)

    def _decode(self) -> int:
        """One decode dispatch over the whole slot array; deliver each
        active slot's token. Returns the iteration's decode token
        SPEND for the chunked-prefill budget (one per decoded slot;
        a speculative window counts k + 1 — docs/DESIGN.md §25).
        Mid-prefill slots (in ``_chunk_state``) are excluded from the
        active set: their streams own pages but must not emit tokens,
        and the batched dispatch's garbage write at their cursor row
        is overwritten by the chunk that commits that position later
        the same iteration. Caller holds ``_step_lock``; the dispatch
        runs outside ``_lock`` over a snapshot of the slot arrays — a
        slot whose stream was failed mid-dispatch (``close()``, crash)
        skips delivery (its cache row write is masked garbage at
        ``j >= length`` for the next occupant, per the refill
        invariant).

        With speculation bound, the two-model window schedule
        (:meth:`_decode_spec`) runs instead — unless any active slot is
        within one window of its token limit, in which case THIS
        iteration falls back to the plain path (a clamped multi-token
        append would land on live rows; the plain path's
        truncate-at-exactly-token_limit contract takes over, and the
        slot finishes within a few iterations)."""
        spec = getattr(self, "_speculative", None)
        if spec is not None:
            with self._lock:
                active = [
                    i for i, s in enumerate(self._slot_stream)
                    if s is not None and i not in self._chunk_state
                ]
                eligible = (
                    bool(active)
                    # Brown-out skips the speculative window but keeps
                    # ``spec`` bound below: the plain path's width-2
                    # draft catch-up still runs, so the draft KV cache
                    # stays in sync and speculation resumes cleanly
                    # when the brown-out releases (docs/DESIGN.md §24).
                    and not self._brownout_active
                    and all(
                        int(self._slot_lengths[i]) + spec.window
                        <= self._engine.token_limit
                        for i in active
                    )
                )
            if not active:
                return 0
            if eligible:
                return self._decode_spec(spec)
        engine = self._engine
        with self._lock:
            self._ensure_active_rows(1)
            snapshot = list(self._slot_stream)
            active = [
                i for i, s in enumerate(snapshot)
                if s is not None and i not in self._chunk_state
            ]
            if not active:
                return 0
            tokens = self._slot_tokens.astype(np.int32)
            lengths = self._slot_lengths.astype(np.int32)
            counts = None
            if spec is not None:
                dlengths = self._slot_draft_state()
                ctokens, counts = self._draft_catchup_window(active, tokens)
        t0 = time.perf_counter()
        nxt = engine.decode(tokens, lengths)
        if spec is not None:
            # Keep the DRAFT cache in sync through plain iterations
            # (the near-capacity fallback): the draft consumes the same
            # token(s) via its width-2 catch-up append, so the
            # gap-is-at-most-one invariant the speculative window
            # relies on holds across any mix of plain and speculative
            # iterations. At draft length == capacity-1 the width-2
            # write clamps one row early and scribbles a live draft
            # row — harmless by exhaustion: that slot's stream is at
            # token_limit - 1 and finishes THIS iteration, so the
            # scribbled row dies with it (the next occupant's prefill
            # + masking make it invisible, per the refill invariant).
            spec.draft_engine.verify(ctokens, dlengths)
        dt_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            delivered = 0
            for slot in active:
                if self._slot_stream[slot] is not snapshot[slot]:
                    continue  # failed by close()/crash mid-dispatch
                self._slot_lengths[slot] += 1
                if counts is not None:
                    self._draft_lengths[slot] = int(
                        dlengths[slot]
                    ) + int(counts[slot])
                    self._draft_pending[slot] = []
                token = int(nxt[slot])
                self._slot_tokens[slot] = token
                self._finish_or_continue(slot, token)
                delivered += 1
            if self._metrics is not None:
                self._metrics.record_decode_step(dt_ms, delivered)
        return len(active)

    def _ensure_active_rows(self, extra: int) -> None:
        """Pre-dispatch page guarantee (paged layout; slot layout:
        no-op): every active slot must hold pages covering ``length +
        extra`` rows before the next decode (``extra=1``) or verify
        window (``extra=w``) writes them. A slot the pool cannot grow
        — even after prefix-cache eviction — fails its stream with
        :class:`RejectedError` (partial tokens stay readable; the
        resubmit lands once other streams release pages). Caller holds
        ``_lock``."""
        for slot, stream in enumerate(self._slot_stream):
            if stream is None or slot in self._chunk_state:
                # Mid-prefill slots already hold pages for the FULL
                # prompt (admit_slot allocates them up front); the
                # batched dispatch's garbage writes past their cursor
                # land in those pages or drop via the OOB sentinel.
                continue
            if self._engine.ensure_rows(
                slot, int(self._slot_lengths[slot]) + int(extra)
            ):
                continue
            if self._metrics is not None:
                self._metrics.record_rejected()
            stream._fail(RejectedError(
                "KV page pool exhausted mid-generation: no free page "
                "for this stream's next token even after prefix-cache "
                "eviction (partial output in tokens_so_far; raise "
                "engine.pool_pages or lower concurrency and resubmit)."
            ))
            self._free_slot(slot)

    def _slot_draft_state(self) -> np.ndarray:
        """Draft cached-rows snapshot (caller holds ``_lock``)."""
        return self._draft_lengths.astype(np.int32).copy()

    def _draft_catchup_window(self, active, cur_tokens):
        """Build the draft's width-2 catch-up/append window: per active
        slot, the (at most one) committed token the draft has not yet
        consumed, then the current input token. Returns ``(tokens
        [slots, 2], counts [slots])`` — ``counts`` is how many of the
        two are real (the rest is padding whose KV row stays garbage
        beyond the advanced length)."""
        n = int(self._engine.slots)
        ctokens = np.zeros((n, 2), np.int32)
        counts = np.zeros((n,), np.int32)
        for i in active:
            pending = self._draft_pending[i]
            if pending:
                ctokens[i, 0] = int(pending[0])
                ctokens[i, 1] = int(cur_tokens[i])
                counts[i] = 2
            else:
                ctokens[i, 0] = int(cur_tokens[i])
                ctokens[i, 1] = int(cur_tokens[i])  # pad row, never valid
                counts[i] = 1
        return ctokens, counts

    def _decode_spec(self, spec) -> int:
        """One speculative window over the whole slot array
        (docs/DESIGN.md §18): the draft proposes ``k`` tokens per slot
        (one width-2 catch-up append + ``k - 1`` draft steps), ONE
        teacher ``decode_verify`` scores all ``k + 1`` positions, and
        greedy acceptance commits the longest draft/teacher prefix
        match plus the teacher's own token at the first mismatch —
        1..k+1 tokens per slot per iteration, mixed accept lengths
        handled as host bookkeeping. Rollback-by-length: rejected
        suffix rows in BOTH caches are never advanced over. Caller
        holds ``_step_lock``; every dispatch runs outside ``_lock``
        over a snapshot, with the same identity-checked commit as the
        plain path."""
        engine = self._engine
        draft = spec.draft_engine
        k = int(spec.k)
        n = int(engine.slots)
        with self._lock:
            # Teacher verify appends the whole window's rows (the
            # accepted prefix advances over them; rejected rows stay
            # masked garbage in allocated pages — rollback never
            # deallocates mid-stream).
            self._ensure_active_rows(spec.window)
            snapshot = list(self._slot_stream)
            active = [
                i for i, s in enumerate(snapshot)
                if s is not None and i not in self._chunk_state
            ]
            if not active:
                return 0
            cur = self._slot_tokens.astype(np.int32).copy()
            lengths = self._slot_lengths.astype(np.int32).copy()
            dlengths = self._slot_draft_state()
            ctokens, counts = self._draft_catchup_window(active, cur)
        t0 = time.perf_counter()
        proposals = np.zeros((n, k), np.int32)
        with _trace.span(
            "spec_draft",
            attrs=(
                {"slots": len(active), "k": k}
                if _trace.enabled()
                else None
            ),
        ):
            # 1. Catch-up + first proposal: one width-2 append brings
            # the draft cache level with the teacher's committed prefix
            # AND consumes the current input token; the last fed
            # position's argmax is the first draft proposal.
            out = draft.verify(ctokens, dlengths)
            step_lengths = dlengths.copy()
            for i in active:
                proposals[i, 0] = int(out[i, int(counts[i]) - 1])
                step_lengths[i] += int(counts[i])
            # 2. k-1 sequential draft steps propose the rest.
            step_tokens = proposals[:, 0].copy()
            for t in range(1, k):
                step_tokens = draft.decode(step_tokens, step_lengths)
                for i in active:
                    proposals[i, t] = int(step_tokens[i])
                    step_lengths[i] += 1
        # 3. ONE teacher dispatch verifies the whole window: input
        # [current, d_1..d_k], argmax scored at every position.
        vtokens = np.zeros((n, k + 1), np.int32)
        for i in active:
            vtokens[i, 0] = int(cur[i])
            vtokens[i, 1:] = proposals[i]
        with _trace.span(
            "spec_verify",
            attrs=(
                {"slots": len(active), "window": k + 1}
                if _trace.enabled()
                else None
            ),
        ):
            scored = engine.verify(vtokens, lengths)
        dt_ms = (time.perf_counter() - t0) * 1e3
        # 4. Host accept + commit (greedy = longest prefix match).
        with self._lock:
            delivered = 0
            proposed_total = 0
            accepted_total = 0
            accept_lengths = []
            for i in active:
                stream = snapshot[i]
                if self._slot_stream[i] is not stream:
                    continue  # failed by close()/crash mid-dispatch
                a = 0
                while a < k and int(proposals[i, a]) == int(scored[i, a]):
                    a += 1
                base = int(lengths[i])
                for j in range(a + 1):
                    # Identical bookkeeping to the plain path, one
                    # accepted token at a time: lengths advance over
                    # the consumed input, then the token is delivered
                    # and EOS/length/capacity checked — a stream that
                    # finishes mid-window discards the rest of the
                    # window (both caches' surplus rows stay masked
                    # garbage per the rollback contract).
                    self._slot_lengths[i] = base + j + 1
                    token = int(scored[i, j])
                    self._slot_tokens[i] = token
                    self._finish_or_continue(i, token)
                    delivered += 1
                    if self._slot_stream[i] is not stream:
                        break
                if self._slot_stream[i] is stream:
                    # Survived the window: the draft has consumed
                    # [current, d_1..d_{k-1}] — on full acceptance it
                    # still owes d_k, carried as the pending catch-up
                    # token for the next window.
                    self._draft_lengths[i] = base + 1 + min(a, k - 1)
                    self._draft_pending[i] = (
                        [int(proposals[i, k - 1])] if a == k else []
                    )
                stream._spec_proposed += k
                stream._spec_accepted += a
                proposed_total += k
                accepted_total += a
                accept_lengths.append(a)
                if _trace.enabled() and stream.rid is not None:
                    _trace.event(
                        "spec_accept",
                        rid=stream.rid,
                        attrs={"proposed": k, "accepted": a},
                    )
            if accept_lengths:
                spec.record_window(proposed_total, accepted_total)
                if self._metrics is not None:
                    self._metrics.record_spec_window(
                        proposed_total,
                        accepted_total,
                        accept_lengths,
                        dt_ms,
                        delivered,
                    )
        return len(active) * (k + 1)

    def _iteration_budget(self) -> int:
        """Tokens one scheduler iteration may spend across decode and
        prefill chunks (docs/DESIGN.md §25). Explicit ``token_budget``
        wins; 0 auto-sizes to full decode occupancy (every slot's
        window) plus one whole chunk, so saturated decode still
        advances exactly one chunk of prefill per iteration."""
        b = int(self.token_budget)
        if b > 0:
            return b
        spec = getattr(self, "_speculative", None)
        per = int(spec.window) if spec is not None else 1
        return int(self._engine.slots) * per + int(
            self._engine.prefill_chunk_tokens
        )

    def _prefill_chunks(self, decode_spend: int) -> None:
        """Spend the iteration's remaining token budget on pending
        prefill chunks (docs/DESIGN.md §25): after decode took
        ``decode_spend`` tokens, the remainder is dealt to mid-prefill
        slots in slot order — up to ``prefill_chunk_tokens`` per lane
        per dispatch, multiple dispatches while budget and pending
        lanes remain. The FINAL chunk of a prompt returns its real
        last-position logits: TTFT is stamped, the first token
        delivered, the prefix cached, and the slot leaves
        ``_chunk_state`` to decode next iteration. Caller holds
        ``_step_lock``; dispatches run outside ``_lock`` with the
        same identity-checked commit as prefill/decode."""
        if not getattr(self, "_chunked", False):
            return
        engine = self._engine
        spec = getattr(self, "_speculative", None)
        chunk_cap = int(engine.prefill_chunk_tokens)
        lane_cap = max(engine._prefill_buckets)
        # Progress floor: even a decode-saturated budget grants one
        # token, so a full slot array can never livelock the pending
        # prefills it is itself waiting on.
        budget = max(1, self._iteration_budget() - int(decode_spend))
        while budget > 0:
            group = []  # (slot, stream, chunk, offset, is_final)
            with self._lock:
                for slot in sorted(self._chunk_state):
                    if len(group) >= lane_cap or budget < 1:
                        break
                    stream = self._slot_stream[slot]
                    if stream is None:
                        continue
                    st = self._chunk_state[slot]
                    pos = int(st["pos"])
                    total = int(stream.prompt.shape[0])
                    c = min(chunk_cap, total - pos, budget)
                    if c < 1:
                        continue
                    budget -= c
                    group.append((
                        slot,
                        stream,
                        stream.prompt[pos:pos + c],
                        pos,
                        pos + c >= total,
                    ))
            if not group:
                return
            t0 = time.perf_counter()
            last = engine.prefill_chunk(
                [g[2] for g in group],
                [g[0] for g in group],
                [g[3] for g in group],
            )
            finals = [g for g in group if g[4]]
            if spec is not None and finals:
                # Seed the DRAFT cache only once the full prompt is
                # committed — the draft keeps its own slot-layout
                # cache and prefills monolithically, exactly like the
                # unchunked admission path (its first-token output is
                # discarded; the teacher's final-chunk token is
                # authoritative).
                spec.draft_engine.prefill(
                    [g[1].prompt for g in finals],
                    [g[0] for g in finals],
                )
            dt_ms = (time.perf_counter() - t0) * 1e3
            with self._lock:
                now = time.perf_counter()
                finished = 0
                stalls = []
                for (slot, stream, chunk, pos, final), tok in zip(
                    group, last
                ):
                    if self._slot_stream[slot] is not stream:
                        continue  # failed by close()/crash mid-dispatch
                    st = self._chunk_state.get(slot)
                    if st is None:
                        continue
                    end = pos + int(np.shape(chunk)[0])
                    st["pos"] = end
                    self._slot_lengths[slot] = end
                    if not final:
                        continue
                    del self._chunk_state[slot]
                    stream.ttft_ms = (now - stream._t_submit) * 1e3
                    stalls.append((now - float(st["admit_t"])) * 1e3)
                    if self._metrics is not None:
                        self._metrics.record_ttft(stream.ttft_ms)
                    if spec is not None:
                        # Both caches hold exactly the prompt now.
                        self._draft_lengths[slot] = end
                        self._draft_pending[slot] = []
                    # Cache the prompt's pages for future warm hits
                    # while the slot still references them.
                    engine.insert_prefix(slot, stream.prompt)
                    self._slot_tokens[slot] = int(tok)
                    self._finish_or_continue(slot, int(tok))
                    finished += 1
                if self._metrics is not None:
                    self._metrics.record_prefill_chunks(len(group), dt_ms)
                    if finished:
                        self._metrics.record_prefill_finish(
                            finished, stalls
                        )
                        self._metrics.record_first_tokens(finished)

    def _update_occupancy(self) -> None:
        if self._metrics is None:
            return
        active_lengths = [
            int(self._slot_lengths[i])
            for i, s in enumerate(self._slot_stream)
            if s is not None
        ]
        self._metrics.record_occupancy(
            len(active_lengths),
            int(self._engine.slots),
            len(self._queue),
            self._engine.kv_pages_in_use(active_lengths),
        )
        pool = self._engine.page_pool
        if pool is not None:
            # Real allocator counts (docs/DESIGN.md §20), not the
            # host-side length estimate the slot layout reports.
            self._metrics.record_pool(
                pool.free_pages, pool.prefix_hit_rate
            )

    def _step_once(self) -> bool:
        """One scheduler iteration: swap boundary, deadline sweeps,
        admit (prefill), decode. Returns whether work remains.

        ``_step_lock`` serializes iterations (sync mode admits
        multi-threaded callers); ``_lock`` guards only the bookkeeping
        phases and is RELEASED across the device dispatches inside
        ``_admit``/``_decode`` so a concurrent ``submit()`` or
        ``/statusz`` ``status()`` never waits out a prefill or decode
        wall time."""
        from zookeeper_tpu.resilience import faults

        with self._step_lock:
            with self._lock:
                plan = faults.active()
                if plan is not None and plan.take_decode_worker_crash():
                    raise WorkerCrashedError(
                        "injected decode scheduler crash "
                        "(FaultPlan.decode_worker_crash)"
                    )
                self._maybe_apply_swap()
                self._maybe_apply_brownout()
                self._expire_queued()
                self._expire_active()
            self._admit()
            spent = self._decode()
            # Chunked prefill rides the SAME iteration after decode:
            # decode spends the budget first, pending chunks get the
            # remainder (docs/DESIGN.md §25). No-op when chunking off.
            self._prefill_chunks(spent)
            with self._lock:
                self._maybe_apply_swap()  # slot array may have drained
                self._maybe_apply_brownout()
                self._update_occupancy()
        # Wake backpressured submitters and drain()/iterator waiters:
        # queue room and stream progress both change per iteration.
        with self._cv:
            self._cv.notify_all()
        return self._has_work()

    def _pump(self) -> bool:
        """_step_once with the crash contract: ANY loop failure fails
        every queued and in-flight stream cleanly (no result() ever
        hangs), then re-raises — the async worker's catch restarts on
        the next submit; synchronous callers see the error with the
        streams already failed."""
        try:
            return self._step_once()
        except BaseException as e:
            self._on_crash(e)
            raise

    def _on_crash(self, error: BaseException) -> None:
        with self._lock:
            streams = [s for s in self._slot_stream if s is not None]
            streams += list(self._queue)
            self._queue.clear()
            for i in range(len(self._slot_stream)):
                self._slot_stream[i] = None
                # Paged layout: drop the failed streams' page
                # references (a dispatch-failure crash already reset
                # the pool wholesale inside the engine — releasing an
                # empty row is a no-op, so both crash shapes leave
                # zero leaked pages, which the chaos suite pins).
                self._engine.release_slot(i)
                # Draft bookkeeping dies with the streams: the next
                # occupant's draft prefill re-seeds it.
                self._draft_lengths[i] = 0
                self._draft_pending[i] = []
            # Mid-prefill cursors die with their streams too (the
            # pages were released above; nothing left to resume).
            self._chunk_state.clear()
            object.__setattr__(self, "_worker", None)
            _trace.event(
                "decode_worker_crash",
                attrs={
                    "error": type(error).__name__,
                    "failed_streams": len(streams),
                },
            )
            if self._metrics is not None:
                self._metrics.record_worker_restart()
            wrapped = WorkerCrashedError(
                f"DecodeScheduler crashed ({error!r}); this stream was "
                "failed cleanly (partial tokens in tokens_so_far) — "
                "resubmit to run on the restarted scheduler."
            )
            wrapped.__cause__ = error
            for stream in streams:
                stream._fail(wrapped)
            self._update_occupancy()
        # Flight-recorder trigger, AFTER the fails (the bundle's
        # RequestLog tail already carries outcome=crashed) and OUTSIDE
        # the lock (a synchronous bundle write must not stall
        # submit()/status() waiting on _lock). One global read when no
        # recorder is installed; never raises (docs/DESIGN.md §16).
        _recorder.notify(
            "decode_worker_crash",
            attrs={
                "error": type(error).__name__,
                "failed_streams": len(streams),
            },
        )

    # -- driving (synchronous mode) --------------------------------------

    def drain(self) -> None:
        """Serve everything: run the loop until the queue and the slot
        array are empty (sync), or block until the worker drains them
        (async; returns early — with streams already failed clean — if
        the worker dies)."""
        self._require_bound()
        if self.synchronous:
            while self._has_work():
                self._pump()
            with self._lock:
                self._maybe_apply_swap()
            return
        self._ensure_worker()
        with self._cv:
            self._cv.notify_all()
        while self._has_work() and not self._stop.is_set():
            worker = getattr(self, "_worker", None)
            if worker is None or not worker.is_alive():
                break  # crash cleanup already failed the streams
            with self._cv:
                self._cv.wait(0.01)

    def _drive(self, stream: DecodeStream, timeout: Optional[float]) -> None:
        """Block/drive until ``stream`` completes; never past its
        deadline."""
        if self.synchronous:
            while not stream._done and self._has_work():
                self._pump()
            if not stream._done and stream.expired():
                if stream._expire() and self._metrics is not None:
                    self._metrics.record_deadline_expired()
            return
        self._ensure_worker()
        with self._cv:
            self._cv.notify_all()
        deadline = stream._deadline_at
        t_end = (
            time.perf_counter() + timeout if timeout is not None else None
        )
        with stream._cond:
            while not stream._done:
                now = time.perf_counter()
                if deadline is not None and now >= deadline:
                    break
                if t_end is not None and now >= t_end:
                    break
                waits = [0.05]
                if deadline is not None:
                    waits.append(deadline - now)
                if t_end is not None:
                    waits.append(t_end - now)
                stream._cond.wait(max(0.0, min(waits)))
        if not stream._done:
            if stream.expired():
                if stream._expire() and self._metrics is not None:
                    self._metrics.record_deadline_expired()
            else:
                raise TimeoutError(
                    f"generation not complete within {timeout}s (worker "
                    "stalled, or close() was called)."
                )

    def _advance(self, stream: DecodeStream) -> None:
        """One increment of progress for an iterating consumer."""
        if self.synchronous:
            if not stream._done and self._has_work():
                self._pump()
            elif not stream._done and stream.expired():
                if stream._expire() and self._metrics is not None:
                    self._metrics.record_deadline_expired()
        else:
            with stream._cond:
                if not stream._done:
                    stream._cond.wait(0.05)
            # The deadline binds the STREAMING consumer too (same
            # posture as result()/_drive): a wedged worker must not
            # block an iterator past the request's deadline.
            if not stream._done and stream.expired():
                if stream._expire() and self._metrics is not None:
                    self._metrics.record_deadline_expired()

    # -- async worker ----------------------------------------------------

    def _ensure_worker(self) -> None:
        # Check-and-spawn under the lock: concurrent first submits must
        # not each start a worker (an orphaned duplicate would keep
        # pumping a closed scheduler — the liveness-under-lock rule the
        # MicroBatcher documents).
        with self._lock:
            worker = getattr(self, "_worker", None)
            if worker is None or not worker.is_alive():
                thread = threading.Thread(
                    target=self._worker_loop,
                    name="zk-decode-scheduler",
                    daemon=True,
                )
                object.__setattr__(self, "_worker", thread)
                thread.start()

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            if not self._has_work() and not self.swap_pending:
                with self._cv:
                    self._cv.wait(0.05)
                continue
            try:
                self._pump()
            except BaseException:
                # Streams already failed clean in _on_crash; the next
                # submit() starts a fresh worker.
                return

    def close(self, drain: bool = False) -> None:
        """Stop the scheduler. ``drain=True`` serves everything first;
        otherwise pending streams are FAILED so no result() blocks
        forever. Safe to call repeatedly / unbound."""
        if getattr(self, "_engine", None) is None:
            return
        if drain:
            try:
                self.drain()
            except Exception:
                pass  # per-stream errors already delivered
        self._stop.set()
        worker = getattr(self, "_worker", None)
        if worker is not None:
            with self._cv:
                self._cv.notify_all()
            worker.join(timeout=5)
            object.__setattr__(self, "_worker", None)
        err = RuntimeError("DecodeScheduler closed with streams pending.")
        with self._lock:
            for stream in list(self._queue):
                stream._fail(err)
            self._queue.clear()
            for i, stream in enumerate(self._slot_stream):
                if stream is not None:
                    stream._fail(err)
                    self._free_slot(i)
        self._stop.clear()

    # -- introspection ---------------------------------------------------

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def active_slots(self) -> int:
        with self._lock:
            return sum(1 for s in self._slot_stream if s is not None)

    def status(self) -> dict:
        """``/statusz`` decode section: the numbers an operator checks
        before trusting the stream metrics."""
        engine = self._engine
        with self._lock:
            active_lengths = [
                int(self._slot_lengths[i])
                for i, s in enumerate(self._slot_stream)
                if s is not None
            ]
            return {
                "slots": int(engine.slots),
                "active_slots": len(active_lengths),
                "queue_depth": len(self._queue),
                "kv_pages_in_use": engine.kv_pages_in_use(active_lengths),
                "kv_capacity_tokens": engine.capacity,
                "kv_cache_mb": round(engine.kv_cache_nbytes / 2**20, 2),
                # HBM accounting (docs/DESIGN.md §17): the provisioned
                # bytes (also the zk_decode_kv_bytes gauge) and the
                # per-slot share an operator sizes capacity with.
                "kv_cache_bytes": int(engine.kv_cache_nbytes),
                "kv_bytes_per_slot": int(
                    engine.kv_cache_nbytes // max(1, int(engine.slots))
                ),
                "decode_attention": engine.decode_attention_flavor,
                # Paged-KV vitals (docs/DESIGN.md §20): layout, pool
                # fill, prefix-cache hits, CoW count — absent pool
                # section means the slot layout.
                "kv_layout": str(engine.kv_layout),
                **(
                    {"kv_pool": engine.pool_status()}
                    if engine.paged
                    else {}
                ),
                # Last dispatch's memory-bandwidth utilization (-1 =
                # unknown) — the roofline lens for the memory-bound
                # decode step.
                "decode_mbu": round(engine.decode_mbu, 4),
                "compiles": engine.compile_count,
                "recompiles_detected": engine.recompiles_detected,
                "swap_pending": self.swap_pending,
                # Speculative schedule vitals (docs/DESIGN.md §18): k,
                # live acceptance, draft compile discipline.
                "speculative": (
                    self._speculative.status()
                    if getattr(self, "_speculative", None) is not None
                    else {"enabled": False}
                ),
                # Chunked-prefill planner vitals (docs/DESIGN.md §25):
                # always present so scrapers need no layout branch;
                # enabled=False means monolithic prefill.
                "chunked_prefill": {
                    "enabled": bool(getattr(self, "_chunked", False)),
                    "chunk_tokens": int(engine.prefill_chunk_tokens),
                    "token_budget": (
                        self._iteration_budget()
                        if getattr(self, "_chunked", False)
                        else 0
                    ),
                    "pending_prefills": len(
                        getattr(self, "_chunk_state", {})
                    ),
                    "pending_prefill_tokens": sum(
                        int(self._slot_stream[i].prompt.shape[0])
                        - int(st["pos"])
                        for i, st in getattr(
                            self, "_chunk_state", {}
                        ).items()
                        if self._slot_stream[i] is not None
                    ),
                },
                # Overload guardrails (docs/DESIGN.md §24): admission
                # estimator state + the scheduler's APPLIED brown-out
                # posture (may lag the guard's intent by one drain).
                "guardrails": {
                    "guard": (
                        self._guard.status()
                        if getattr(self, "_guard", None) is not None
                        else {"enabled": False}
                    ),
                    "brownout_active": bool(
                        getattr(self, "_brownout_active", False)
                    ),
                },
            }
