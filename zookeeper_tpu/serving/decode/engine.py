"""The autoregressive decode engine: two compiled programs over
device-resident KV-cache state.

The forward-only serving engine re-runs the full context per token —
O(s^2) work per emitted token and no sequence state between requests.
This engine is the real decode path (ROADMAP item 1): the KV cache
lives on device as engine state (``cache.py`` — per-layer
``[slots, capacity, heads, head_dim]`` buffers, slots sharded on the
data axes and heads on the model axis via the Partitioner rule
tables), and exactly TWO program families serve all traffic:

- **prefill** — bucketed like the forward engine (``prefill_buckets``
  x ``seq_buckets`` shape buckets, one AOT compile each at
  ``warmup()``): runs the ordinary full-context forward over a
  right-padded prompt group, scatters every layer's K/V heads into the
  group's slots, and emits each request's FIRST token (the TTFT
  token). Ledgered as ``prefill`` in the ProgramLedger.
- **decode_step** — ONE program regardless of traffic: one token for
  every slot in the slot array per dispatch (inactive slots compute
  masked garbage that is never delivered — the fixed shape is what
  makes slot refill compile-free). Ledgered as ``decode_step``.

Compilation discipline is the forward engine's, verbatim: explicit
compile cache keyed on (program, buckets, mesh), ``warmup()``
pre-compiles everything, ``compile_count`` pins at zero growth after
warmup, and any post-warmup dispatch-path compile bumps
``zk_serving_recompiles_total`` + a ``recompile_detected`` trace event
(a recompile mid-traffic is a serving stall, and with continuous
batching it stalls EVERY active stream at once).

The cache is DONATED through both programs (the update is in-place on
device; the engine always adopts the returned reference), while the
weights are never donated and are read through ONE reference per
dispatch — ``swap_weights`` is therefore atomic per dispatch exactly
like the forward engine's (the per-SEQUENCE weight-version contract
lives a level up, in ``DecodeScheduler.request_swap``).

Two eras extend the grid without changing the discipline: the
speculative ``verify_step`` family (docs/DESIGN.md §18, one compile
per window width), and ``kv_layout="paged"`` (§20) — the same program
shapes re-expressed over a SHARED page pool with per-slot page tables
as runtime operands, plus the warm-prefix ``prefill_extend`` family
(suffix-only admission over cache-resident prefix pages) and the
one-page ``copy_page`` CoW primitive. Every member is AOT-warmed and
ledgered; ``compile_count`` still pins at zero growth under traffic.
"""

import logging
import time
from typing import Any, Optional, Sequence

import numpy as np

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.observability import trace as _trace
from zookeeper_tpu.serving.decode.cache import (
    allocate_kv_cache,
    kv_cache_bytes,
    pages_in_use,
)
from zookeeper_tpu.serving.decode.pages import (
    PagePool,
    allocate_page_pool,
    page_pool_bytes,
)

logger = logging.getLogger(__name__)

__all__ = ["DecodeEngine"]


@component
class DecodeEngine:
    """Paged/ring KV-cache decode engine over a cached-attention LM
    module (``TransformerLMModule``-shaped: ``prefill`` and
    ``decode_step`` apply methods sharing the ``__call__`` weights).

    Configure the slot array and buckets as Fields; bind the runtime
    objects with :meth:`bind`. The engine is the DEVICE half only —
    request queueing, slot assignment, EOS/deadline bookkeeping and
    streaming live in :class:`~zookeeper_tpu.serving.decode.scheduler.\
DecodeScheduler`.
    """

    #: Concurrent sequence slots — the decode program's fixed batch.
    #: More slots = more sequences per dispatch (throughput) at
    #: slots x capacity KV HBM; keep it a multiple of the mesh's
    #: data-axis product to serve with a sharded cache.
    slots: int = Field(8)
    #: Prompt-length buckets for the prefill program (ascending). One
    #: compile per (prefill_bucket, seq_bucket) pair at warmup; a
    #: prompt rides the smallest bucket that holds it (right padding —
    #: causal attention keeps padded rows out of the emitted token).
    seq_buckets: Sequence[int] = Field((16, 64))
    #: Batch buckets for the prefill program: how many queued requests
    #: one prefill dispatch admits together. Default singleton — one
    #: request per prefill keeps warmup cheap; widen under high
    #: admission rates.
    prefill_buckets: Sequence[int] = Field((1,))
    #: Per-slot KV capacity in TOKENS. -1 sizes it to the module's
    #: positional table (``max_seq_len`` — nothing can decode past it
    #: anyway); an explicit smaller value caps memory and truncates
    #: generation at capacity. Rounded up to a ``page_size`` multiple.
    kv_capacity: int = Field(-1)
    #: KV page granularity (tokens): the accounting/alignment unit for
    #: capacity and the ``kv_pages_in_use`` occupancy numbers, and the
    #: nesting unit for the paged decode kernel's KV read blocks.
    page_size: int = Field(16)
    #: Cache-attention flavor for the decode_step program
    #: (docs/DESIGN.md §17): "auto" runs the length-aware Pallas paged
    #: decode kernel on TPU and the reference einsum elsewhere
    #: (interpret-mode Pallas on CPU is a numerics vehicle, not a
    #: serving path — the same posture the bench takes for flash);
    #: "pallas" forces the kernel (interpret off-TPU), "reference"
    #: forces the oracle einsum, "module" defers to the module's own
    #: ``decode_attention`` setting/injected callable. Unsupported
    #: geometry (see ``ops.decode_attention_supported``) degrades
    #: "auto"/"pallas" to the reference with a warning — the
    #: compile_forward small-bucket posture.
    decode_attention: str = Field("auto")
    #: Program-naming prefix for the ProgramLedger / recompile events
    #: (docs/DESIGN.md §18): a speculative-decode DRAFT engine runs the
    #: same program family as the teacher in the same process, and the
    #: ledger/statusz must tell them apart — ``SpeculativeDecoding``
    #: binds its draft engine with ``ledger_prefix="draft_"`` so its
    #: programs ledger as ``draft_prefill`` / ``draft_decode_step`` /
    #: ``draft_verify_step`` next to the teacher's.
    ledger_prefix: str = Field("")
    #: KV storage layout (docs/DESIGN.md §20): "slots" (the §15
    #: per-slot contiguous buffers — worst-case provisioned, zero
    #: indirection on the hot path; the certified default) or "paged"
    #: (a SHARED device page pool + per-slot page tables as runtime
    #: operands: capacity is pooled across slots, warm prompt prefixes
    #: share pages through the radix prefix cache with copy-on-write,
    #: and admission sheds on pool exhaustion instead of slot count
    #: alone). Token-parity discipline is identical in both layouts.
    kv_layout: str = Field("slots")
    #: Total pool pages per layer (paged layout only). -1 sizes the
    #: pool to ``slots × capacity/page_size`` — worst-case parity with
    #: the slot layout, useful for certification; production sets it
    #: SMALLER than worst case (that is the entire point of pooling:
    #: resident tokens are bounded by actual lengths, not slot count ×
    #: capacity) with admission shedding as the backstop.
    pool_pages: int = Field(-1)
    #: KV quantization for the paged pool: "none" (rows in the model
    #: compute dtype) or "int8" (rows stored int8 with page-shaped
    #: per-(row, head) float32 scales, dequantized inside the attention
    #: read — double the resident tokens per HBM byte, documented-ULP
    #: numerics; docs/DESIGN.md §20).
    kv_quant: str = Field("none")
    #: Radix prefix cache over prompt prefixes (paged layout only):
    #: warm-prefix admissions skip prefill for cache-resident pages
    #: (the warm-extend program computes only the suffix) with
    #: copy-on-write at the divergence point and LRU eviction under
    #: pool pressure. Off = every admission prefills cold (the pool
    #: still pools capacity).
    prefix_cache: bool = Field(True)
    #: Chunked prefill (docs/DESIGN.md §25; paged layout only, like
    #: ``kv_quant``): > 0 splits every admitted prompt into chunks of
    #: at most this many tokens, each a :meth:`prefill_chunk` dispatch
    #: the scheduler interleaves with decode steps under its token
    #: budget — a long prompt stops freezing in-flight streams for its
    #: whole prefill. 0 (default) keeps the monolithic prefill. Must
    #: not exceed the largest seq bucket (chunks ride the warmed
    #: ``prefill_extend`` width grid — zero new compiles).
    prefill_chunk_tokens: int = Field(0)

    # -- binding ---------------------------------------------------------

    def bind(
        self,
        module: Any,
        params: Any,
        model_state: Any = None,
        *,
        partitioner: Any = None,
    ) -> "DecodeEngine":
        """Attach the LM module to decode. ``module`` must expose the
        cached-attention seam (``prefill`` / ``decode_step`` methods
        plus the ``num_layers/num_heads/d_model/max_seq_len/dtype``
        geometry attributes — ``TransformerLMModule`` does).
        ``partitioner`` defaults to single-device; pass the training
        partitioner to decode under the training dp/tp layout (KV slots
        shard over the data axes, heads over the model axis)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        for method in ("prefill", "decode_step"):
            if not hasattr(module, method):
                raise ValueError(
                    f"DecodeEngine needs a module with a {method!r} "
                    "apply method (the cached-attention decode seam — "
                    "see TransformerLMModule); got "
                    f"{type(module).__name__}."
                )
        seq_buckets = tuple(int(s) for s in self.seq_buckets)
        if not seq_buckets or any(s < 1 for s in seq_buckets) or list(
            seq_buckets
        ) != sorted(set(seq_buckets)):
            raise ValueError(
                f"seq_buckets={self.seq_buckets!r} must be a non-empty, "
                "strictly-ascending tuple of positive lengths."
            )
        prefill_buckets = tuple(int(b) for b in self.prefill_buckets)
        if not prefill_buckets or any(
            b < 1 for b in prefill_buckets
        ) or list(prefill_buckets) != sorted(set(prefill_buckets)):
            raise ValueError(
                f"prefill_buckets={self.prefill_buckets!r} must be a "
                "non-empty, strictly-ascending tuple of positive sizes."
            )
        if self.slots < 1:
            raise ValueError(f"slots={self.slots} must be >= 1.")
        if max(prefill_buckets) > self.slots:
            raise ValueError(
                f"largest prefill bucket {max(prefill_buckets)} exceeds "
                f"slots={self.slots}; a prefill group can never admit "
                "more sequences than there are slots."
            )
        if self.page_size < 1:
            raise ValueError(f"page_size={self.page_size} must be >= 1.")
        position_cap = int(module.max_seq_len)
        if self.kv_capacity == -1:
            capacity = position_cap
        elif self.kv_capacity > 0:
            capacity = int(self.kv_capacity)
        else:
            raise ValueError(
                f"kv_capacity={self.kv_capacity}: expected a positive "
                "token capacity or -1 (size to the positional table)."
            )
        # Page-align up: the layout unit a paged kernel would gather.
        capacity = -(-capacity // self.page_size) * self.page_size
        if max(seq_buckets) > capacity:
            raise ValueError(
                f"largest seq bucket {max(seq_buckets)} exceeds the KV "
                f"capacity {capacity}; shrink the buckets or raise "
                "kv_capacity."
            )
        if max(seq_buckets) > position_cap:
            # warmup() TRACES the prefill program at every bucket; a
            # bucket past the positional table would die inside the
            # module's forward — fail here with the config-level story.
            raise ValueError(
                f"largest seq bucket {max(seq_buckets)} exceeds the "
                f"module's positional table ({position_cap}); prompts "
                "can never be that long."
            )

        if str(self.decode_attention) not in (
            "auto", "pallas", "reference", "module"
        ):
            raise ValueError(
                f"decode_attention={self.decode_attention!r}: expected "
                "'auto', 'pallas', 'reference', or 'module'."
            )
        if str(self.kv_layout) not in ("slots", "paged"):
            raise ValueError(
                f"kv_layout={self.kv_layout!r}: expected 'slots' or "
                "'paged'."
            )
        if str(self.kv_quant) not in ("none", "int8"):
            raise ValueError(
                f"kv_quant={self.kv_quant!r}: expected 'none' or 'int8'."
            )
        paged = str(self.kv_layout) == "paged"
        if not paged and str(self.kv_quant) != "none":
            raise ValueError(
                "kv_quant='int8' requires kv_layout='paged' (the slot "
                "layout stores rows in the compute dtype; quantization "
                "lives with the page pool — docs/DESIGN.md §20)."
            )
        if int(self.prefill_chunk_tokens) < 0:
            raise ValueError(
                f"prefill_chunk_tokens={self.prefill_chunk_tokens}: "
                "expected 0 (monolithic prefill) or a positive chunk "
                "size in tokens."
            )
        if int(self.prefill_chunk_tokens) > 0:
            # The same loud paged-only seam as kv_quant: the chunk
            # program appends through the page table at arbitrary row
            # offsets — the slot layout has no such program, and a
            # silent fall-back to monolithic prefill would misreport
            # every ITL plan built on chunking (docs/DESIGN.md §25).
            if not paged:
                raise ValueError(
                    "prefill_chunk_tokens requires kv_layout='paged' "
                    "(chunks append through the page table via the "
                    "prefill_extend program family; the slot layout "
                    "always prefills monolithically — docs/DESIGN.md "
                    "§25). Set engine.kv_layout='paged' or "
                    "prefill_chunk_tokens=0."
                )
            if int(self.prefill_chunk_tokens) > max(seq_buckets):
                raise ValueError(
                    f"prefill_chunk_tokens={self.prefill_chunk_tokens} "
                    f"exceeds the largest seq bucket {max(seq_buckets)}"
                    ": chunks ride the warmed prefill_extend width "
                    "grid, so a chunk wider than every bucket would "
                    "compile on the dispatch path; shrink the chunk or "
                    "widen seq_buckets."
                )
        max_pages = capacity // int(self.page_size)
        if paged:
            for method in ("decode_step_paged", "decode_verify_paged"):
                if not hasattr(module, method):
                    raise ValueError(
                        f"kv_layout='paged' needs a module with a "
                        f"{method!r} apply method (the page-pool decode "
                        "seam — see TransformerLMModule); got "
                        f"{type(module).__name__}."
                    )
            if self.pool_pages == -1:
                num_pages = int(self.slots) * max_pages
            elif self.pool_pages > 0:
                num_pages = int(self.pool_pages)
            else:
                raise ValueError(
                    f"pool_pages={self.pool_pages}: expected a positive "
                    "page count or -1 (worst-case parity with the slot "
                    "layout)."
                )
            if num_pages < max_pages:
                raise ValueError(
                    f"pool_pages={num_pages} below capacity/page_size="
                    f"{max_pages}: one full-capacity sequence could "
                    "never be served; raise pool_pages or shrink "
                    "kv_capacity."
                )
        else:
            num_pages = 0
        if partitioner is None:
            from zookeeper_tpu.parallel.partitioner import (
                SingleDevicePartitioner,
            )

            partitioner = SingleDevicePartitioner()
        partitioner.setup()
        object.__setattr__(self, "_module", module)
        object.__setattr__(self, "_partitioner", partitioner)
        object.__setattr__(self, "_seq_buckets", seq_buckets)
        object.__setattr__(self, "_prefill_buckets", prefill_buckets)
        object.__setattr__(self, "_capacity", capacity)
        object.__setattr__(self, "_position_cap", position_cap)
        object.__setattr__(self, "_paged", paged)
        object.__setattr__(self, "_num_pages", num_pages)
        object.__setattr__(self, "_max_pages", max_pages)
        # Host-side page allocator + table + radix prefix cache
        # (docs/DESIGN.md §20). The device pool tree rides _cache.
        object.__setattr__(
            self,
            "_pool",
            PagePool(
                num_pages=num_pages,
                page_size=int(self.page_size),
                slots=int(self.slots),
                max_pages_per_slot=max_pages,
                prefix_cache=bool(self.prefix_cache),
            )
            if paged
            else None,
        )

        variables = {"params": params, **dict(model_state or {})}
        object.__setattr__(
            self, "_variables", self._place_variables(variables)
        )

        head_dim = int(module.d_model) // int(module.num_heads)
        cache = self._allocate_cache()
        mesh = partitioner.mesh
        cache_sharding = None
        cache_replicated = mesh is not None
        if mesh is not None:
            cache_sharding = (
                partitioner.page_pool_sharding(cache)
                if paged
                else partitioner.decode_cache_sharding(cache)
            )
            if cache_sharding is not None:
                # Divisibility: slots over the data axes, heads over the
                # model axis. When the shapes cannot split, fall back to
                # a fully-replicated cache (correct, memory-redundant)
                # rather than dying — the compile_forward small-bucket
                # posture.
                try:
                    jax.tree.map(
                        lambda x, s: s.shard_shape(np.shape(x)),
                        cache,
                        cache_sharding,
                    )
                    cache_replicated = False
                except (ValueError, ZeroDivisionError) as e:
                    logger.warning(
                        "KV cache [slots=%d, heads=%d] does not divide "
                        "over the %s mesh (%s); decoding with a "
                        "REPLICATED cache — size slots/heads in "
                        "multiples of the mesh axes to shard",
                        self.slots,
                        int(module.num_heads),
                        dict(mesh.shape),
                        e,
                    )
                    cache_sharding = jax.tree.map(
                        lambda _: NamedSharding(mesh, PartitionSpec()),
                        cache,
                    )
        object.__setattr__(self, "_cache_sharding", cache_sharding)
        object.__setattr__(self, "_cache_replicated", cache_replicated)
        object.__setattr__(self, "_cache", self._place_cache(cache))
        if paged:
            nbytes = page_pool_bytes(
                int(module.num_layers),
                num_pages,
                int(self.page_size),
                int(module.num_heads),
                head_dim,
                np.dtype(module.dtype).itemsize,
                quant=str(self.kv_quant),
            )
        else:
            nbytes = kv_cache_bytes(
                int(module.num_layers),
                int(self.slots),
                capacity,
                int(module.num_heads),
                head_dim,
                np.dtype(module.dtype).itemsize,
            )
        object.__setattr__(self, "_cache_nbytes", nbytes)
        object.__setattr__(self, "_compiled_cache", {})
        object.__setattr__(self, "_compile_count", 0)
        object.__setattr__(self, "_warmed", False)
        object.__setattr__(self, "_recompiles_detected", 0)
        object.__setattr__(self, "_ledger_records", {})
        flavor, attn_fn = self._resolve_decode_attention()
        object.__setattr__(self, "_decode_attention_flavor", flavor)
        object.__setattr__(self, "_decode_attention_fn", attn_fn)
        self._publish_bind_gauges()
        return self

    def _resolve_decode_attention(self):
        """Resolve the ``decode_attention`` Field into ``(flavor_tag,
        override_fn)`` — the callable threaded into the decode_step
        trace (None = defer to the module's own setting).

        "auto" selects the paged kernel only on a real TPU backend:
        interpret-mode Pallas on CPU is a grid-loop INTERPRETER, orders
        of magnitude slower than the fused einsum — the same reason the
        bench runs dense prefill off-TPU. On a mesh the kernel is
        wrapped in ``sharded_paged_decode_attention`` (slots over the
        data axes, heads over the model axis — or fully replicated
        specs when the cache took the replicated fallback), because
        GSPMD would otherwise gather the whole cache around the opaque
        pallas call."""
        import jax

        from zookeeper_tpu import ops

        module = self._module
        paged = bool(getattr(self, "_paged", False))
        choice = str(self.decode_attention)
        if choice == "module":
            return "module", None
        if choice == "auto":
            choice = (
                "pallas" if jax.default_backend() == "tpu" else "reference"
            )
        reference = (
            ops.pool_decode_attention if paged else ops.cached_attention
        )
        if choice == "reference":
            return "reference", reference
        heads = int(module.num_heads)
        head_dim = int(module.d_model) // heads
        if not ops.decode_attention_supported(heads, head_dim):
            logger.warning(
                "decode_attention='pallas' requested but head_dim=%d is "
                "off the kernel's supported geometry (see "
                "ops.decode_attention_supported); decoding with the "
                "REFERENCE einsum instead",
                head_dim,
            )
            return "reference", reference
        from functools import partial

        mesh = self._partitioner.mesh
        if mesh is None:
            if paged:
                # Page size / block policy come from the pool shapes.
                return "pallas", ops.pool_paged_decode_attention
            return "pallas", partial(
                ops.paged_decode_attention, page_size=int(self.page_size)
            )
        # The SAME axis derivation the cache placement used: a
        # disagreement here would make GSPMD reshard the cache around
        # the kernel every dispatch.
        data_axes, model_axis = self._partitioner.decode_cache_axes()
        sharded_kwargs = dict(
            mesh=mesh,
            data_axes=data_axes,
            model_axis=model_axis,
            replicated=bool(self._cache_replicated),
        )
        if paged:
            return "pallas", partial(
                ops.sharded_pool_paged_decode_attention, **sharded_kwargs
            )
        return "pallas", partial(
            ops.sharded_paged_decode_attention,
            page_size=int(self.page_size),
            **sharded_kwargs,
        )

    def _publish_bind_gauges(self) -> None:
        """Bind-time decode gauges: the provisioned KV HBM
        (``zk_decode_kv_bytes`` — computed since PR 9 but never
        exported) and the MBU gauge registered at its -1 unknown
        sentinel so a pre-traffic scrape renders the series."""
        from zookeeper_tpu.observability.registry import default_registry

        reg = default_registry()
        reg.gauge(
            "zk_decode_kv_bytes",
            help="HBM provisioned for the decode KV cache (k+v, all "
            "layers, full slot capacity)",
        ).set(float(self._cache_nbytes))
        # Handle kept on the engine: _observe_decode runs once per
        # decode dispatch and must not pay the registry lock + lookup
        # per token.
        object.__setattr__(self, "_mbu_gauge", reg.gauge(
            "zk_decode_mbu",
            help="last decode dispatch: ledger cost-analysis bytes / "
            "wall time / reference HBM bandwidth (-1 = bytes or "
            "bandwidth unknown); an UPPER bound with the paged kernel "
            "(static analysis counts full buffers, the kernel reads "
            "length-bounded blocks)",
            initial=-1,
        ))

    def decode_mbu_for(self, seconds: float, program: str = "decode_step") -> float:
        """MBU of a decode-path program (default ``decode_step``; the
        speculative hot loop passes its ``verify_step/w{N}`` key) at a
        given dispatch wall time: ledger cost-analysis bytes /
        ``seconds`` / reference HBM bandwidth, -1 when any input is
        unknown (the ``ledger.mbu`` totality contract — never raises).
        The live gauge evaluates this at each dispatch's own time; the
        bench evaluates it at the run's MEDIAN dispatch time so the
        gated ``decode_mbu`` key is not a single-sample ratio of the
        least-representative (drain-tail) dispatch."""
        from zookeeper_tpu.observability import ledger as _ledger

        bw = getattr(self, "_hbm_bandwidth", None)
        if bw is None:
            from zookeeper_tpu.observability.peaks import (
                reference_hbm_bandwidth,
            )

            bw = reference_hbm_bandwidth()[0]
            object.__setattr__(self, "_hbm_bandwidth", bw)
        record = self._ledger_records.get(
            str(self.ledger_prefix) + program
        )
        value = _ledger.mbu(
            getattr(record, "bytes_accessed", None), seconds, bw
        )
        return float(value) if value is not None else -1.0

    def _observe_decode(
        self, seconds: float, program: str = "decode_step"
    ) -> None:
        """Publish ``zk_decode_mbu`` for one completed (readback-
        bounded) decode-path dispatch — the memory-bound counterpart of
        the forward engine's ``zk_serve_mfu`` (the decode loop is
        HBM-bound, so FLOPs-based MFU is the wrong lens; docs/DESIGN.md
        §17). Under speculation the hot program is ``verify_step``, not
        ``decode_step`` — ``verify()`` feeds the gauge too, so the
        roofline tracks whichever program actually serves. Total: a
        gauge update never raises."""
        if seconds <= 0:
            return
        value = self.decode_mbu_for(seconds, program)
        # Per-engine copy FIRST: the gauge is process-global (the
        # export path), so with two engines live the gauge holds
        # whichever dispatched last — decode_mbu/statusz must report
        # THIS engine's number.
        object.__setattr__(self, "_last_decode_mbu", value)
        self._mbu_gauge.set(value)

    @property
    def decode_attention_flavor(self) -> str:
        """The RESOLVED decode-attention flavor this engine serves with
        ("pallas" / "reference" / "module") — after auto-selection and
        any unsupported-geometry degrade."""
        self._require_bound()
        return self._decode_attention_flavor

    @property
    def decode_mbu(self) -> float:
        """THIS engine's last decode dispatch's memory-bandwidth
        utilization (-1 = unknown / no dispatch yet). Deliberately the
        per-engine copy, not the process-global ``zk_decode_mbu``
        gauge: with two engines in one process (the bench A/B, flavor
        tests) the gauge holds whichever engine dispatched last."""
        return float(getattr(self, "_last_decode_mbu", -1.0))

    def _place_variables(self, variables: Any) -> Any:
        """One placement path shared by ``bind`` and ``swap_weights`` —
        same contract as the forward engine's."""
        import jax

        sharding = self._partitioner.variables_sharding(variables)
        if sharding is not None:
            return jax.tree.map(jax.device_put, variables, sharding)
        return jax.device_put(variables)

    def _require_bound(self) -> None:
        if getattr(self, "_module", None) is None:
            raise RuntimeError(
                "DecodeEngine is not bound: call engine.bind(module, "
                "params, model_state) before warmup()/prefill()/decode()."
            )

    def _allocate_cache(self):
        """The ONE cache-geometry call (``bind`` and ``_reset_cache``
        must allocate identical trees — a layout change made in one
        place would serve post-crash resubmits from a diverged cache).
        Layout-dispatched: the slot-contiguous buffers or the shared
        page pool (docs/DESIGN.md §20)."""
        module = self._module
        head_dim = int(module.d_model) // int(module.num_heads)
        if getattr(self, "_paged", False):
            return allocate_page_pool(
                int(module.num_layers),
                self._num_pages,
                int(self.page_size),
                int(module.num_heads),
                head_dim,
                module.dtype,
                quant=str(self.kv_quant),
            )
        return allocate_kv_cache(
            int(module.num_layers),
            int(self.slots),
            self._capacity,
            int(module.num_heads),
            head_dim,
            module.dtype,
        )

    def _place_cache(self, cache):
        """Place a cache tree under the bound sharding (replicated /
        sharded / single-device) — shared by ``bind`` and
        ``_reset_cache``."""
        import jax

        if self._cache_sharding is not None:
            return jax.tree.map(jax.device_put, cache, self._cache_sharding)
        return jax.device_put(cache)

    def _reset_cache(self) -> None:
        """Reallocate a fresh zeroed KV cache under the bound sharding.

        The dispatch path DONATES the cache buffers; if the compiled
        call itself raises (transient device/runtime failure), the old
        buffers may already be invalidated while the success-path
        reference assignment never ran — without this reset every later
        dispatch would die on deleted arrays, breaking the scheduler's
        resubmit-after-restart contract. A zeroed cache is consistent:
        a crash fails every in-flight stream, so no slot's previous
        contents are live. In the paged layout the HOST allocator is
        reset with the device pool (refcounts zeroed, every page free,
        prefix trie dropped — its nodes indexed bytes that no longer
        exist): the chaos suite pins zero leaked pages across this
        path."""
        object.__setattr__(
            self, "_cache", self._place_cache(self._allocate_cache())
        )
        if getattr(self, "_pool", None) is not None:
            self._pool.reset()

    # -- geometry --------------------------------------------------------

    @property
    def capacity(self) -> int:
        """Per-slot KV capacity in tokens (page-aligned)."""
        self._require_bound()
        return self._capacity

    @property
    def position_cap(self) -> int:
        """The module's positional-table bound: no sequence can extend
        past ``min(position_cap, capacity)`` total tokens."""
        self._require_bound()
        return self._position_cap

    @property
    def token_limit(self) -> int:
        """Hard per-sequence total-token bound (prompt + generated)."""
        return min(self.capacity, self.position_cap)

    @property
    def max_prompt(self) -> int:
        """Longest admissible prompt (the largest seq bucket)."""
        self._require_bound()
        return max(self._seq_buckets)

    @property
    def kv_cache_nbytes(self) -> int:
        self._require_bound()
        return self._cache_nbytes

    def kv_pages_in_use(self, lengths) -> int:
        """Occupancy accounting for the gauge/statusz. The paged layout
        reports the REAL allocator count (pages the free list has
        handed out — prefix-cache-retained pages included, because they
        genuinely occupy pool HBM); the slot layout keeps the §15
        host-side estimate ``Σ ceil(len/page)`` over the ACTIVE slots'
        ``lengths``."""
        if getattr(self, "_paged", False):
            return int(self._pool.used_pages)
        return pages_in_use(lengths, int(self.page_size))

    # -- page lifecycle (the scheduler-facing paged surface) -------------
    #
    # Every method is callable in BOTH layouts so the scheduler never
    # branches on kv_layout: the slot layout answers with the trivial
    # (always-cold, always-fits, nothing-to-release) degenerate.

    @property
    def paged(self) -> bool:
        self._require_bound()
        return bool(self._paged)

    @property
    def page_pool(self):
        """The host-side :class:`~zookeeper_tpu.serving.decode.pages.\
PagePool` (None in the slot layout)."""
        self._require_bound()
        return self._pool

    def admit_slot(
        self, slot: int, prompt, *, copy: bool = True
    ) -> Optional[dict]:
        """Admission-time page allocation for ``slot``'s ``prompt``
        (docs/DESIGN.md §20): prefix-cache lookup, page-table row
        build, and (``copy=True``) copy-on-write execution for a
        mid-page divergence. Returns the plan (``{"shared_tokens":
        int}``, plus the pending ``"cow": (src, dst)`` when
        ``copy=False`` — the scheduler's split: host bookkeeping under
        its lock, the device copy outside via :meth:`copy_page`) or
        None when the pool is exhausted (nothing allocated — the
        caller requeues or sheds). Slot layout: always the trivial
        cold plan."""
        if not getattr(self, "_paged", False):
            return {"shared_tokens": 0, "cow": None}
        plan = self._pool.assign_prompt(int(slot), prompt)
        if plan is None:
            return None
        if copy:
            cow = plan.pop("cow")
            if cow is not None:
                self.copy_page(*cow)
            plan["cow"] = None
        return plan

    def ensure_rows(self, slot: int, rows: int) -> bool:
        """Pre-dispatch guarantee that ``slot``'s pages cover ``rows``
        total KV rows (decode needs ``length + 1``; a verify window
        ``length + w``). False = pool exhausted after prefix-cache
        eviction. Slot layout: trivially True (capacity is
        pre-provisioned)."""
        if not getattr(self, "_paged", False):
            return True
        return self._pool.ensure_rows(int(slot), int(rows))

    def release_slot(self, slot: int) -> None:
        """Stream finished/failed: drop the slot's page references
        (prefix-cache-shared pages stay resident for warm hits)."""
        if getattr(self, "_paged", False):
            self._pool.release_slot(int(slot))

    def insert_prefix(self, slot: int, prompt) -> int:
        """Cache the admitted prompt's pages for future warm hits
        (called after the prefill/extend dispatch landed them)."""
        if not getattr(self, "_paged", False):
            return 0
        return self._pool.insert_prefix(int(slot), prompt)

    def invalidate_prefix_cache(self) -> int:
        """Drop every cached prefix page (weight hot-swap: cached K/V
        belongs to the OLD weights). Returns nodes dropped."""
        if not getattr(self, "_paged", False):
            return 0
        return self._pool.invalidate_prefix()

    def pool_status(self) -> Optional[dict]:
        """The ``/statusz`` ``kv_pool`` sub-section (None in the slot
        layout)."""
        if not getattr(self, "_paged", False):
            return None
        return self._pool.status()

    @property
    def compile_count(self) -> int:
        """XLA compiles so far. After ``warmup()`` this is exactly
        ``len(prefill_buckets) * len(seq_buckets) + 1`` and continuous
        slot refill must never move it."""
        return getattr(self, "_compile_count", 0)

    @property
    def recompiles_detected(self) -> int:
        """Post-warmup dispatch-path compiles (mirrored to
        ``zk_serving_recompiles_total``)."""
        return getattr(self, "_recompiles_detected", 0)

    def seq_bucket_for(self, length: int) -> int:
        for s in self._seq_buckets:
            if s >= length:
                return s
        raise ValueError(
            f"prompt of {length} tokens exceeds the largest seq bucket "
            f"{max(self._seq_buckets)}; widen seq_buckets."
        )

    def prefill_bucket_for(self, n: int) -> int:
        for b in self._prefill_buckets:
            if b >= n:
                return b
        raise ValueError(
            f"prefill group of {n} exceeds the largest prefill bucket "
            f"{max(self._prefill_buckets)}."
        )

    # -- compile cache ---------------------------------------------------

    def _note_dispatch_compile(self, key) -> None:
        """Post-warmup compile on the dispatch path: the recompile
        watchdog (shared counter with the forward engine — one series
        alerts on ALL serving stalls)."""
        from zookeeper_tpu.observability.registry import default_registry

        object.__setattr__(
            self,
            "_recompiles_detected",
            getattr(self, "_recompiles_detected", 0) + 1,
        )
        default_registry().counter(
            "zk_serving_recompiles_total",
            help="post-warmup compiles triggered on the request "
            "path (each one is a serving stall)",
        ).inc()
        _trace.event("recompile_detected", attrs={"program": str(key)})
        # Flight-recorder trigger (docs/DESIGN.md §16): with continuous
        # batching a dispatch-path recompile stalls EVERY active
        # stream — bundle the evidence while their spans exist.
        from zookeeper_tpu.observability import recorder as _recorder

        _recorder.notify(
            "recompile_detected", attrs={"program": str(key)}
        )
        logger.warning(
            "post-warmup decode-engine recompile on the dispatch path "
            "(%s): every active stream is stalling on XLA — warm the "
            "full bucket grid",
            key,
        )

    def _replicated(self):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = self._partitioner.mesh
        if mesh is None:
            return None
        return NamedSharding(mesh, PartitionSpec())

    def _aot(
        self,
        key: str,
        fn,
        example_args,
        *,
        donate_cache_at: Optional[int],
        with_variables: bool = True,
        cache_only_output: bool = False,
        cache_like_at: tuple = (),
    ):
        """AOT lower+compile ``fn`` with the engine's sharding
        discipline, timed and recorded in the process ProgramLedger
        under ``key`` ('prefill' / 'decode_step' / 'verify_step' /
        'copy_page', ``ledger_prefix``-tagged — a draft engine's
        programs ledger as ``draft_*``). ``with_variables=False`` is
        the variables-free program shape (``copy_page``: cache first);
        ``cache_only_output=True`` marks programs returning ONLY the
        (cache-sharded) cache-shaped tree instead of ``(cache, out)``.
        ``donate_cache_at=None`` compiles a READ-ONLY program (the
        page-gather export must leave the pool intact);
        ``cache_like_at`` names extra arg positions carrying
        cache-sharded trees (a scatter's incoming page block)."""
        import jax

        key = str(self.ledger_prefix) + key

        donate = () if donate_cache_at is None else (donate_cache_at,)
        mesh = self._partitioner.mesh
        if mesh is None:
            jitted = jax.jit(fn, donate_argnums=donate)
        else:
            repl = self._replicated()
            cache_sh = self._cache_sharding
            in_shardings = []
            for i in range(len(example_args)):
                if with_variables and i == 0:
                    vars_sh = self._partitioner.variables_sharding(
                        self._variables
                    )
                    if vars_sh is None:
                        vars_sh = jax.tree.map(
                            lambda _: repl, self._variables
                        )
                    in_shardings.append(vars_sh)
                elif i == donate_cache_at or i in cache_like_at:
                    # NamedSharding is shape-agnostic along unsharded
                    # dims, so the pool's per-leaf shardings apply to a
                    # same-structure page BLOCK (leading dim W, not
                    # num_pages) verbatim.
                    in_shardings.append(cache_sh)
                else:
                    in_shardings.append(repl)
            out_shardings = (
                cache_sh if cache_only_output else (cache_sh, repl)
            )
            jitted = jax.jit(
                fn,
                in_shardings=tuple(in_shardings),
                out_shardings=out_shardings,
                donate_argnums=donate,
            )
        t0 = time.perf_counter()
        lowered = jitted.lower(*example_args)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        from zookeeper_tpu.observability.ledger import default_ledger

        mesh_desc = (
            "x".join(f"{k}:{v}" for k, v in mesh.shape.items())
            if mesh is not None
            else "1"
        )
        record = default_ledger().record(
            key.split("/")[0],
            f"{type(self._partitioner).__name__}/mesh={mesh_desc}/{key}",
            lowered=lowered,
            compiled=compiled,
            lower_ms=(t1 - t0) * 1e3,
            compile_ms=(t2 - t1) * 1e3,
            attrs={"slots": int(self.slots)},
        )
        # Keep the row (cost-analysis bytes feed the decode MBU gauge).
        self._ledger_records[key] = record
        object.__setattr__(self, "_compile_count", self._compile_count + 1)
        return compiled

    def _decode_compiled(self, *, during_dispatch: bool = False):
        import jax
        import jax.numpy as jnp

        self._require_bound()
        key = ("decode_step", self._partitioner.mesh)
        cached = self._compiled_cache.get(key)
        if cached is not None:
            return cached
        if during_dispatch and self._warmed:
            self._note_dispatch_compile("decode_step")
        module = self._module
        # Static by closure: the resolved decode-attention flavor (the
        # paged kernel, its sharded wrapper, or the reference einsum)
        # is part of THIS compiled program's identity.
        attn_override = getattr(self, "_decode_attention_fn", None)
        n = int(self.slots)
        if self._paged:

            def decode_fn(variables, cache, tokens, lengths, table):
                logits, new_cache = module.apply(
                    variables, tokens, lengths, cache, table,
                    method="decode_step_paged",
                    attention_override=attn_override,
                )
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return new_cache, nxt

            example = (
                self._variables,
                self._cache,
                jax.ShapeDtypeStruct((n,), np.int32),
                jax.ShapeDtypeStruct((n,), np.int32),
                jax.ShapeDtypeStruct((n, self._max_pages), np.int32),
            )
        else:

            def decode_fn(variables, cache, tokens, lengths):
                logits, new_cache = module.apply(
                    variables, tokens, lengths, cache,
                    method="decode_step",
                    attention_override=attn_override,
                )
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return new_cache, nxt

            example = (
                self._variables,
                self._cache,
                jax.ShapeDtypeStruct((n,), np.int32),
                jax.ShapeDtypeStruct((n,), np.int32),
            )
        compiled = self._aot(
            "decode_step", decode_fn, example, donate_cache_at=1
        )
        self._compiled_cache[key] = compiled
        return compiled

    def _prefill_compiled(
        self, pb: int, sb: int, *, during_dispatch: bool = False
    ):
        import jax
        import jax.numpy as jnp

        self._require_bound()
        key = ("prefill", pb, sb, self._partitioner.mesh)
        cached = self._compiled_cache.get(key)
        if cached is not None:
            return cached
        if during_dispatch and self._warmed:
            self._note_dispatch_compile(f"prefill/b{pb}s{sb}")
        module = self._module
        if self._paged:
            ps = int(self.page_size)
            num_pages = int(self._num_pages)

            def prefill_fn(variables, cache, tokens, lengths, slot_rows):
                from zookeeper_tpu.models.transformer import (
                    _pool_write_rows,
                )

                last_logits, kv = module.apply(
                    variables, tokens, lengths, method="prefill"
                )
                # Scatter each prompt row through its slot's page-table
                # row: position j lands at (slot_rows[:, j // ps],
                # j % ps). Rows past the true length, unallocated table
                # entries, and a partial group's padding rows (all
                # -1 rows) take the OOB page sentinel and write
                # nowhere — the paged twin of the slot-id drop.
                j = jnp.arange(sb)
                row = jnp.clip(j // ps, 0, slot_rows.shape[1] - 1)
                pages = slot_rows[:, row]  # [pb, sb]
                dead = (j[None, :] >= lengths[:, None]) | (pages < 0)
                pages = jnp.where(dead, num_pages, pages)
                offs = jnp.broadcast_to(j % ps, pages.shape)
                new_cache = []
                for layer, (k, v) in zip(cache, kv):
                    new_cache.append(
                        _pool_write_rows(
                            layer, {"k": k, "v": v}, pages, offs
                        )
                    )
                first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
                return tuple(new_cache), first

            example = (
                self._variables,
                self._cache,
                jax.ShapeDtypeStruct((pb, sb), np.int32),
                jax.ShapeDtypeStruct((pb,), np.int32),
                jax.ShapeDtypeStruct((pb, self._max_pages), np.int32),
            )
        else:

            def prefill_fn(variables, cache, tokens, lengths, slot_ids):
                last_logits, kv = module.apply(
                    variables, tokens, lengths, method="prefill"
                )
                new_cache = []
                for layer, (k, v) in zip(cache, kv):
                    # Scatter the group's K/V heads into its slots'
                    # first sb rows. mode="drop": the PADDING rows of a
                    # partial group carry slot id == slots (out of
                    # bounds) and must write nowhere.
                    new_cache.append({
                        "k": layer["k"].at[slot_ids, :sb].set(
                            k, mode="drop"
                        ),
                        "v": layer["v"].at[slot_ids, :sb].set(
                            v, mode="drop"
                        ),
                    })
                first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
                return tuple(new_cache), first

            example = (
                self._variables,
                self._cache,
                jax.ShapeDtypeStruct((pb, sb), np.int32),
                jax.ShapeDtypeStruct((pb,), np.int32),
                jax.ShapeDtypeStruct((pb,), np.int32),
            )
        compiled = self._aot(
            f"prefill/b{pb}s{sb}", prefill_fn, example, donate_cache_at=1
        )
        self._compiled_cache[key] = compiled
        return compiled

    def _verify_compiled(self, width: int, *, during_dispatch: bool = False):
        """The multi-token verify/append program (docs/DESIGN.md §18):
        ``width`` tokens per slot through ``decode_verify`` in one
        dispatch — the speculative teacher runs it at ``k + 1``, the
        draft at its catch-up width. One compile per width, part of the
        warmed grid (``warmup_verify``); ledgered as ``verify_step``
        (``ledger_prefix``-tagged)."""
        import jax
        import jax.numpy as jnp

        self._require_bound()
        if width < 1:
            raise ValueError(f"verify width={width} must be >= 1.")
        if width > self._capacity:
            raise ValueError(
                f"verify width {width} exceeds the KV capacity "
                f"{self._capacity}; shrink speculative.k or raise "
                "kv_capacity."
            )
        key = ("verify", int(width), self._partitioner.mesh)
        cached = self._compiled_cache.get(key)
        if cached is not None:
            return cached
        if during_dispatch and self._warmed:
            self._note_dispatch_compile(f"verify_step/w{width}")
        module = self._module
        n = int(self.slots)
        if self._paged:

            def verify_fn(variables, cache, tokens, lengths, table):
                logits, new_cache = module.apply(
                    variables, tokens, lengths, cache, table,
                    method="decode_verify_paged",
                )
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return new_cache, nxt

            example = (
                self._variables,
                self._cache,
                jax.ShapeDtypeStruct((n, int(width)), np.int32),
                jax.ShapeDtypeStruct((n,), np.int32),
                jax.ShapeDtypeStruct((n, self._max_pages), np.int32),
            )
        else:

            def verify_fn(variables, cache, tokens, lengths):
                logits, new_cache = module.apply(
                    variables, tokens, lengths, cache,
                    method="decode_verify",
                )
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return new_cache, nxt

            example = (
                self._variables,
                self._cache,
                jax.ShapeDtypeStruct((n, int(width)), np.int32),
                jax.ShapeDtypeStruct((n,), np.int32),
            )
        compiled = self._aot(
            f"verify_step/w{width}", verify_fn, example, donate_cache_at=1
        )
        self._compiled_cache[key] = compiled
        return compiled

    def _extend_compiled(
        self, pb: int, w: int, *, during_dispatch: bool = False
    ):
        """The WARM-prefix prefill program (paged layout + prefix
        cache, docs/DESIGN.md §20): a group whose prompts share
        cache-resident prefixes enters ``decode_verify_paged`` with
        each prompt's SUFFIX as the window — the shared pages are read
        through the page table, never recomputed, and the emitted first
        token comes from each row's true-last window position. One
        compile per (prefill bucket, width bucket), part of the warmed
        grid; ledgered ``prefill_extend``."""
        import jax
        import jax.numpy as jnp

        self._require_bound()
        key = ("extend", int(pb), int(w), self._partitioner.mesh)
        cached = self._compiled_cache.get(key)
        if cached is not None:
            return cached
        if during_dispatch and self._warmed:
            self._note_dispatch_compile(f"prefill_extend/b{pb}w{w}")
        module = self._module

        def extend_fn(
            variables, cache, tokens, lengths, slot_rows, valid, out_idx
        ):
            logits, new_cache = module.apply(
                variables, tokens, lengths, cache, slot_rows,
                method="decode_verify_paged", valid=valid,
            )
            last = jnp.take_along_axis(
                logits,
                jnp.clip(out_idx, 0, int(w) - 1)[:, None, None],
                axis=1,
            )[:, 0]
            first = jnp.argmax(last, axis=-1).astype(jnp.int32)
            return new_cache, first

        example = (
            self._variables,
            self._cache,
            jax.ShapeDtypeStruct((int(pb), int(w)), np.int32),
            jax.ShapeDtypeStruct((int(pb),), np.int32),
            jax.ShapeDtypeStruct((int(pb), self._max_pages), np.int32),
            jax.ShapeDtypeStruct((int(pb),), np.int32),
            jax.ShapeDtypeStruct((int(pb),), np.int32),
        )
        compiled = self._aot(
            f"prefill_extend/b{pb}w{w}", extend_fn, example,
            donate_cache_at=1,
        )
        self._compiled_cache[key] = compiled
        return compiled

    def _copy_page_compiled(self, *, during_dispatch: bool = False):
        """The copy-on-write program (docs/DESIGN.md §20): copy ONE
        pool page (every per-layer k/v row + scale page) from ``src``
        to ``dst`` on device. Runs once per divergence-mid-page
        admission — rare and tiny, so one page per dispatch keeps it a
        single warmed shape."""
        import jax

        self._require_bound()
        key = ("copy_page", self._partitioner.mesh)
        cached = self._compiled_cache.get(key)
        if cached is not None:
            return cached
        if during_dispatch and self._warmed:
            self._note_dispatch_compile("copy_page")

        def copy_fn(cache, src, dst):
            out = []
            for layer in cache:
                out.append(
                    {
                        name: buf.at[dst].set(buf[src])
                        for name, buf in layer.items()
                    }
                )
            return tuple(out)

        example = (
            self._cache,
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((), np.int32),
        )
        compiled = self._aot(
            "copy_page", copy_fn, example, donate_cache_at=0,
            with_variables=False, cache_only_output=True,
        )
        self._compiled_cache[key] = compiled
        return compiled

    def _gather_pages_compiled(self, *, during_dispatch: bool = False):
        """The page-EXPORT program (disaggregated handoff, docs/
        DESIGN.md §22): gather ``transfer_width`` pool pages (every
        per-layer k/v row + scale page) into a contiguous page block —
        the unit a :class:`~zookeeper_tpu.serving.disagg.transfer.\
PageTransfer` moves between mesh slices. READ-ONLY: the source pool
        is NOT donated (the prefill role keeps serving, and a
        prefix-cache-shared page may be mid-read by another lane)."""
        import jax

        self._require_bound()
        key = ("gather_pages", self._partitioner.mesh)
        cached = self._compiled_cache.get(key)
        if cached is not None:
            return cached
        if during_dispatch and self._warmed:
            self._note_dispatch_compile("gather_pages")

        def gather_fn(cache, ids):
            out = []
            for layer in cache:
                out.append(
                    {name: buf[ids] for name, buf in layer.items()}
                )
            return tuple(out)

        example = (
            self._cache,
            jax.ShapeDtypeStruct((self.transfer_width,), np.int32),
        )
        compiled = self._aot(
            "gather_pages", gather_fn, example, donate_cache_at=None,
            with_variables=False, cache_only_output=True,
            cache_like_at=(0,),
        )
        self._compiled_cache[key] = compiled
        return compiled

    def _scatter_pages_compiled(self, *, during_dispatch: bool = False):
        """The page-IMPORT program (docs/DESIGN.md §22): scatter a
        transferred page block into this engine's pool at the adopted
        page ids. Padding ids carry the OOB page sentinel
        (``num_pages``) and write nowhere (``mode="drop"`` — the paged
        prefill's idiom); the pool is donated like every other
        cache-writing dispatch."""
        import jax
        import jax.numpy as jnp

        self._require_bound()
        key = ("scatter_pages", self._partitioner.mesh)
        cached = self._compiled_cache.get(key)
        if cached is not None:
            return cached
        if during_dispatch and self._warmed:
            self._note_dispatch_compile("scatter_pages")
        num_pages = int(self._num_pages)

        def scatter_fn(cache, block, ids):
            ids = jnp.where(ids < 0, num_pages, ids)
            out = []
            for layer, blk in zip(cache, block):
                out.append(
                    {
                        name: buf.at[ids].set(blk[name], mode="drop")
                        for name, buf in layer.items()
                    }
                )
            return tuple(out)

        block_example = tuple(
            {
                name: jax.ShapeDtypeStruct(
                    (self.transfer_width,) + tuple(np.shape(buf)[1:]),
                    buf.dtype,
                )
                for name, buf in layer.items()
            }
            for layer in self._cache
        )
        example = (
            self._cache,
            block_example,
            jax.ShapeDtypeStruct((self.transfer_width,), np.int32),
        )
        compiled = self._aot(
            "scatter_pages", scatter_fn, example, donate_cache_at=0,
            with_variables=False, cache_only_output=True,
            cache_like_at=(1,),
        )
        self._compiled_cache[key] = compiled
        return compiled

    @property
    def transfer_width(self) -> int:
        """Fixed page count of one transfer block: the pages a
        max-seq-bucket prompt writes — every handoff rides this ONE
        compiled shape (shorter prompts pad; docs/DESIGN.md §22)."""
        self._require_bound()
        if not self._paged:
            raise RuntimeError(
                "page transfer is a paged-layout program; "
                "kv_layout='slots' has no page pool to export."
            )
        return self._pool.pages_for(max(self._seq_buckets))

    def warmup_transfer(self) -> None:
        """Pre-compile the page export/import programs BEFORE handoff
        traffic (the disaggregated bind calls this for both roles — a
        transfer compile after ``warmup()`` is deliberate grid growth,
        not a dispatch-path recompile)."""
        self._gather_pages_compiled()
        self._scatter_pages_compiled()

    def export_pages(self, page_ids: Sequence[int]):
        """Gather ``page_ids``'s pool pages into a transfer block (the
        device-side handoff unit). Padding lanes gather page 0 —
        harmless garbage the import side's OOB sentinel drops. The pool
        is untouched (read-only program); returns the block tree."""
        self._require_bound()
        w = self.transfer_width
        n = len(page_ids)
        if not 0 < n <= w:
            raise ValueError(
                f"export_pages moves 1..{w} pages per block, got {n}."
            )
        ids = np.zeros((w,), np.int32)
        ids[:n] = [int(p) for p in page_ids]
        compiled = self._gather_pages_compiled(during_dispatch=True)
        with _trace.span(
            "export_pages_dispatch",
            attrs={"pages": n} if _trace.enabled() else None,
        ):
            return compiled(self._cache, ids)

    def import_pages(self, block, page_ids: Sequence[int]) -> None:
        """Scatter a transferred ``block`` into this pool at the
        adopted ``page_ids`` (the destination half of the handoff —
        pages come from :meth:`~zookeeper_tpu.serving.decode.pages.\
PagePool.adopt_slot`). ``block`` must already be placed on this
        engine's devices; the caller (``PageTransfer``) owns the move."""
        self._require_bound()
        w = self.transfer_width
        n = len(page_ids)
        if not 0 < n <= w:
            raise ValueError(
                f"import_pages lands 1..{w} pages per block, got {n}."
            )
        ids = np.full((w,), int(self._num_pages), np.int32)  # OOB drop
        ids[:n] = [int(p) for p in page_ids]
        compiled = self._scatter_pages_compiled(during_dispatch=True)
        with _trace.span(
            "import_pages_dispatch",
            attrs={"pages": n} if _trace.enabled() else None,
        ):
            try:
                new_cache = compiled(self._cache, block, ids)
            except BaseException:
                self._reset_cache()  # donation consumed the buffers
                raise
            object.__setattr__(self, "_cache", new_cache)

    def warmup_verify(self, width: int) -> None:
        """Pre-compile the verify program at ``width`` (the speculative
        bind calls this for the teacher's ``k + 1`` and the draft's
        catch-up width BEFORE traffic — a verify compile after
        ``warmup()`` is deliberate grid growth here, not a dispatch-path
        recompile)."""
        self._verify_compiled(int(width))

    def warmup(self) -> int:
        """Pre-compile the full program grid (every prefill bucket pair
        + the decode step; the paged layout adds the warm-extend grid
        when the prefix cache is on, and the copy-on-write page copy)
        so no stream ever waits on XLA; a speculative bind extends the
        grid with its verify widths via :meth:`warmup_verify`. Returns
        the number of cached executables."""
        self._require_bound()
        for pb in self._prefill_buckets:
            for sb in self._seq_buckets:
                self._prefill_compiled(pb, sb)
        self._decode_compiled()
        if self._paged:
            self._copy_page_compiled()
            # The extend grid serves BOTH warm-prefix admissions and
            # chunked prefill (docs/DESIGN.md §25) — chunk dispatches
            # bucket their width into the same (pb, sb) pairs, so a
            # chunked engine with the prefix cache off still needs the
            # full grid warmed.
            if self.prefix_cache or int(self.prefill_chunk_tokens) > 0:
                for pb in self._prefill_buckets:
                    for sb in self._seq_buckets:
                        self._extend_compiled(pb, sb)
        object.__setattr__(self, "_warmed", True)
        return len(self._compiled_cache)

    # -- dispatch --------------------------------------------------------

    def prefill(self, prompts: Sequence[np.ndarray], slot_ids: Sequence[int]):
        """Admit a group: write each prompt's KV into its slot and emit
        each sequence's FIRST token. ``prompts`` are 1-D int arrays (up
        to the largest prefill bucket of them, each at most
        ``max_prompt`` tokens); ``slot_ids`` the target slots (unique).
        Returns the first tokens as a host ``[len(prompts)] int32``
        array. The TTFT token: the scheduler stamps time-to-first-token
        off this call's readback."""
        import jax

        self._require_bound()
        n = len(prompts)
        if n == 0:
            return np.zeros((0,), np.int32)
        if n != len(set(int(s) for s in slot_ids)) or n != len(slot_ids):
            raise ValueError(
                f"slot_ids {list(slot_ids)!r} must be unique and match "
                f"the {n} prompts."
            )
        lens = [int(np.shape(p)[0]) for p in prompts]
        if min(lens) < 1:
            raise ValueError("empty prompt is not servable.")
        pb = self.prefill_bucket_for(n)
        sb = self.seq_bucket_for(max(lens))
        tokens = np.zeros((pb, sb), np.int32)
        lengths = np.ones((pb,), np.int32)  # pad rows: len 1, dropped
        for i, (p, _) in enumerate(zip(prompts, slot_ids)):
            tokens[i, : lens[i]] = np.asarray(p, np.int32)
            lengths[i] = lens[i]
        if self._paged:
            # Page-table rows instead of slot ids: padding rows stay
            # all -1 (every write drops via the OOB page sentinel).
            ids = np.full((pb, self._max_pages), -1, np.int32)
            for i, s in enumerate(slot_ids):
                ids[i] = self._pool.table[int(s)]
        else:
            ids = np.full((pb,), int(self.slots), np.int32)  # OOB drop
            for i, s in enumerate(slot_ids):
                ids[i] = int(s)
        compiled = self._prefill_compiled(pb, sb, during_dispatch=True)
        with _trace.span(
            "prefill_dispatch",
            attrs=(
                {"requests": n, "bucket": pb, "seq_bucket": sb}
                if _trace.enabled()
                else None
            ),
        ):
            try:
                new_cache, first = compiled(
                    self._variables, self._cache, tokens, lengths, ids
                )
            except BaseException:
                # Donation already consumed the old buffers: restore a
                # usable (zeroed) cache before propagating so the
                # restarted scheduler can serve resubmits.
                self._reset_cache()
                raise
            object.__setattr__(self, "_cache", new_cache)
            first = np.asarray(jax.device_get(first))
        return first[:n].astype(np.int32)

    def prefill_warm(
        self,
        prompts: Sequence[np.ndarray],
        slot_ids: Sequence[int],
        shared_lens: Sequence[int],
    ):
        """Warm-prefix admission (paged layout, docs/DESIGN.md §20):
        each prompt's first ``shared_lens[i]`` tokens are already
        resident in cache-shared pages, so only the SUFFIX rides the
        device — through the ``prefill_extend`` program at the smallest
        width bucket holding the longest suffix. Emits each request's
        first token exactly like :meth:`prefill`; the TTFT collapse for
        warm prefixes is this method's whole reason to exist."""
        import jax

        self._require_bound()
        if not self._paged:
            raise RuntimeError(
                "prefill_warm is a paged-layout dispatch; slots-mode "
                "admissions always run the cold prefill."
            )
        n = len(prompts)
        if n == 0:
            return np.zeros((0,), np.int32)
        suffixes = [
            int(np.shape(p)[0]) - int(sh)
            for p, sh in zip(prompts, shared_lens)
        ]
        if min(suffixes) < 1:
            raise ValueError(
                "warm prefill needs >= 1 suffix token per prompt (the "
                "prefix match is capped at len - 1 so the first "
                "emission's logits exist)."
            )
        pb = self.prefill_bucket_for(n)
        w = self.seq_bucket_for(max(suffixes))
        tokens = np.zeros((pb, w), np.int32)
        lengths = np.zeros((pb,), np.int32)
        valid = np.zeros((pb,), np.int32)  # pad rows: 0 valid, dropped
        out_idx = np.zeros((pb,), np.int32)
        rows = np.full((pb, self._max_pages), -1, np.int32)
        for i, (p, s, sh) in enumerate(zip(prompts, slot_ids, shared_lens)):
            p = np.asarray(p, np.int32)
            suf = p[int(sh):]
            tokens[i, : suf.shape[0]] = suf
            lengths[i] = int(sh)
            valid[i] = suf.shape[0]
            out_idx[i] = suf.shape[0] - 1
            rows[i] = self._pool.table[int(s)]
        compiled = self._extend_compiled(pb, w, during_dispatch=True)
        with _trace.span(
            "prefill_warm_dispatch",
            attrs=(
                {"requests": n, "bucket": pb, "width": w}
                if _trace.enabled()
                else None
            ),
        ):
            try:
                new_cache, first = compiled(
                    self._variables, self._cache, tokens, lengths, rows,
                    valid, out_idx,
                )
            except BaseException:
                self._reset_cache()  # donation consumed the buffers
                raise
            object.__setattr__(self, "_cache", new_cache)
            first = np.asarray(jax.device_get(first))
        return first[:n].astype(np.int32)

    def prefill_chunk(
        self,
        chunks: Sequence[np.ndarray],
        slot_ids: Sequence[int],
        offsets: Sequence[int],
    ):
        """Chunked-prefill append (paged layout, docs/DESIGN.md §25):
        write each lane's ``chunks[i]`` KV rows at positions
        ``offsets[i]..offsets[i] + len(chunks[i]) - 1`` of its slot,
        through the slot's page-table row. This is the warm-extend
        program with the CURSOR as the resident prefix: ``lengths`` is
        the offset (rows below it are already committed — earlier
        chunks or prefix-cache pages), ``valid`` masks the padding
        past each chunk, and the returned per-lane token is the argmax
        at each chunk's LAST position — meaningful only on a lane's
        FINAL chunk (where that position is the prompt's last token:
        the first emission), discarded by the scheduler otherwise.
        Token identity with monolithic prefill is the §20 warm-extend
        certification applied per chunk: every row is written exactly
        once with full causal context over the committed prefix. Rides
        the warmed ``prefill_extend`` (bucket, width) grid — zero new
        compiles for any chunk within the seq buckets."""
        import jax

        self._require_bound()
        if not self._paged:
            raise RuntimeError(
                "prefill_chunk is a paged-layout dispatch; slots-mode "
                "admissions always run the monolithic prefill."
            )
        n = len(chunks)
        if n == 0:
            return np.zeros((0,), np.int32)
        lens = [int(np.shape(c)[0]) for c in chunks]
        if min(lens) < 1:
            raise ValueError(
                "prefill_chunk needs >= 1 token per lane (zero-token "
                "chunks must be skipped by the planner)."
            )
        pb = self.prefill_bucket_for(n)
        w = self.seq_bucket_for(max(lens))
        tokens = np.zeros((pb, w), np.int32)
        lengths = np.zeros((pb,), np.int32)
        valid = np.zeros((pb,), np.int32)  # pad rows: 0 valid, dropped
        out_idx = np.zeros((pb,), np.int32)
        rows = np.full((pb, self._max_pages), -1, np.int32)
        for i, (c, s, off) in enumerate(zip(chunks, slot_ids, offsets)):
            c = np.asarray(c, np.int32)
            tokens[i, : lens[i]] = c
            lengths[i] = int(off)
            valid[i] = lens[i]
            out_idx[i] = lens[i] - 1
            rows[i] = self._pool.table[int(s)]
        compiled = self._extend_compiled(pb, w, during_dispatch=True)
        with _trace.span(
            "prefill_chunk_dispatch",
            attrs=(
                {"lanes": n, "bucket": pb, "width": w,
                 "tokens": int(sum(lens))}
                if _trace.enabled()
                else None
            ),
        ):
            try:
                new_cache, last = compiled(
                    self._variables, self._cache, tokens, lengths, rows,
                    valid, out_idx,
                )
            except BaseException:
                self._reset_cache()  # donation consumed the buffers
                raise
            object.__setattr__(self, "_cache", new_cache)
            last = np.asarray(jax.device_get(last))
        return last[:n].astype(np.int32)

    def copy_page(self, src: int, dst: int) -> None:
        """Execute one copy-on-write page copy on device (the
        ``assign_prompt`` plan's ``cow`` entry) BEFORE the dispatch
        that writes into ``dst``."""
        self._require_bound()
        compiled = self._copy_page_compiled(during_dispatch=True)
        try:
            new_cache = compiled(
                self._cache,
                np.int32(int(src)),
                np.int32(int(dst)),
            )
        except BaseException:
            self._reset_cache()  # donation consumed the buffers
            raise
        object.__setattr__(self, "_cache", new_cache)

    def decode(self, tokens: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """One token for EVERY slot: feed the current input token per
        slot (each sits at position ``lengths[slot]``), write its K/V,
        and return the argmax next token per slot as a host ``[slots]
        int32`` array. Inactive slots ride along (fixed shape) — the
        scheduler ignores their output and never advances their
        lengths."""
        import jax

        self._require_bound()
        tokens = np.asarray(tokens, np.int32)
        lengths = np.asarray(lengths, np.int32)
        if tokens.shape != (int(self.slots),) or lengths.shape != (
            int(self.slots),
        ):
            raise ValueError(
                f"decode expects [slots]={self.slots} token and length "
                f"arrays, got {tokens.shape} / {lengths.shape}."
            )
        compiled = self._decode_compiled(during_dispatch=True)
        args = (tokens, lengths)
        if self._paged:
            args = (tokens, lengths, np.ascontiguousarray(self._pool.table))
        with _trace.span(
            "decode_dispatch",
            attrs=(
                {"slots": int(self.slots)} if _trace.enabled() else None
            ),
        ):
            t0 = time.perf_counter()
            try:
                new_cache, nxt = compiled(
                    self._variables, self._cache, *args
                )
            except BaseException:
                self._reset_cache()  # donation consumed the buffers
                raise
            object.__setattr__(self, "_cache", new_cache)
            nxt = np.asarray(jax.device_get(nxt))
            # Readback-bounded wall time — the only honest dispatch
            # clock (the compiled call returns un-synced arrays).
            self._observe_decode(time.perf_counter() - t0)
        return nxt.astype(np.int32)

    def verify(self, tokens: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        """``w`` tokens for EVERY slot in one dispatch (docs/DESIGN.md
        §18): feed the window's input tokens per slot (token ``j`` sits
        at position ``lengths[slot] + j``), append all ``w`` K/V rows,
        and return the argmax next token AT EACH POSITION as a host
        ``[slots, w] int32`` array — ``out[s, j]`` is the greedy token
        after consuming input ``j``, the verify scores the scheduler's
        prefix-match acceptance reads. The CALLER owns the rollback:
        only ``lengths`` it subsequently advances count as appended;
        rejected rows stay masked garbage. Active slots must satisfy
        ``lengths + w <= capacity`` (the scheduler's eligibility check)
        — inactive slots ride along clamped and ignored."""
        import jax

        self._require_bound()
        tokens = np.asarray(tokens, np.int32)
        lengths = np.asarray(lengths, np.int32)
        if (
            tokens.ndim != 2
            or tokens.shape[0] != int(self.slots)
            or lengths.shape != (int(self.slots),)
        ):
            raise ValueError(
                f"verify expects [slots={self.slots}, w] tokens and "
                f"[slots] lengths, got {tokens.shape} / {lengths.shape}."
            )
        w = int(tokens.shape[1])
        compiled = self._verify_compiled(w, during_dispatch=True)
        args = (tokens, lengths)
        if self._paged:
            args = (tokens, lengths, np.ascontiguousarray(self._pool.table))
        with _trace.span(
            "verify_dispatch",
            attrs=(
                {"slots": int(self.slots), "width": w}
                if _trace.enabled()
                else None
            ),
        ):
            t0 = time.perf_counter()
            try:
                new_cache, nxt = compiled(
                    self._variables, self._cache, *args
                )
            except BaseException:
                self._reset_cache()  # donation consumed the buffers
                raise
            object.__setattr__(self, "_cache", new_cache)
            nxt = np.asarray(jax.device_get(nxt))
            # Readback-bounded: under speculation THIS is the hot
            # program, so it feeds the MBU roofline gauge like decode.
            self._observe_decode(
                time.perf_counter() - t0, program=f"verify_step/w{w}"
            )
        return nxt.astype(np.int32)

    # -- hot swap --------------------------------------------------------

    def check_swap(self, params: Any, model_state: Any = None) -> Any:
        """Validate a candidate weight set against the bound one
        (structure + leaf shapes/dtypes — the compiled programs serve
        ONE architecture) WITHOUT applying it. Returns the assembled
        variables dict. Raises ``ValueError`` on mismatch."""
        import jax

        self._require_bound()
        new = {"params": params, **dict(model_state or {})}
        cur = self._variables
        want_s, got_s = jax.tree.structure(cur), jax.tree.structure(new)
        if want_s != got_s:
            raise ValueError(
                "swap_weights: new variables tree does not match the "
                f"bound structure (bound {want_s}, got {got_s}); the "
                "compiled decode programs serve ONE architecture."
            )
        bad = [
            f"{np.shape(g)}/{np.dtype(getattr(g, 'dtype', type(g)))} where "
            f"the engine serves {np.shape(w)}/{np.dtype(w.dtype)}"
            for w, g in zip(jax.tree.leaves(cur), jax.tree.leaves(new))
            if tuple(np.shape(g)) != tuple(np.shape(w))
            or np.dtype(getattr(g, "dtype", np.float32)) != np.dtype(w.dtype)
        ]
        if bad:
            raise ValueError(
                "swap_weights: leaf shape/dtype mismatch — "
                + "; ".join(bad[:4])
                + (" ..." if len(bad) > 4 else "")
                + ". The compiled prefill/decode programs were compiled "
                "for the bound shapes; a differently-sized checkpoint "
                "needs a fresh bind()."
            )
        return new

    def swap_weights(self, params: Any, model_state: Any = None) -> None:
        """Atomically replace the decoded weights WITHOUT recompiling
        (one reference assignment; each dispatch reads the reference
        once). NOTE: with continuous batching, per-DISPATCH atomicity
        is not per-SEQUENCE atomicity — an in-flight stream would
        straddle weight versions. ``DecodeScheduler.request_swap`` is
        the seam that upholds the one-version-per-sequence contract;
        call this directly only when no streams are in flight."""
        new = self.check_swap(params, model_state)
        with _trace.span("weight_swap"):
            placed = self._place_variables(new)
            object.__setattr__(self, "_variables", placed)
