"""Speculative decoding: a draft model proposes, the teacher verifies
(docs/DESIGN.md §18).

The decode engine's throughput is bounded by one teacher ``decode_step``
dispatch per emitted token. Greedy speculative decoding amortizes that
to one ``verify_step`` dispatch per window: a small DRAFT model
autoregressively proposes ``k`` tokens per slot, one batched teacher
``decode_verify`` scores all ``k + 1`` window positions in a single
dispatch (multi-token KV append, ``cache.append_kv_rows``), and the
scheduler keeps the longest prefix where the draft's proposals match
the teacher's greedy argmax — plus the teacher's own token at the first
mismatch, which the verify already computed for free. Greedy
speculation is LOSSLESS by construction: every emitted token is the
teacher's argmax given the committed prefix, so speculative output is
certified token-identical to plain greedy decode — a perfect fit for
this repo's bit-exactness test policy (the rejected suffix is rolled
back by simply not advancing ``lengths``; garbage rows beyond a slot's
length are already certified harmless by the §17 poisoned-row tests).

This component owns the DRAFT half: a second :class:`DecodeEngine`
mirroring the teacher's slot/bucket/capacity geometry (same
``decode_cache_sharding`` seam, same partitioner, its own KV cache and
AOT program family, ledgered ``draft_*`` with ``compile_count`` pinned
zero post-warmup). The repo uniquely already owns both model halves:
``training/distill.py`` produces aligned student/teacher pairs — point
``draft_checkpoint`` at the distilled student's export. The two-model
slot SCHEDULE lives in :class:`DecodeScheduler` (``_decode_spec``);
the config surface is ``LMServingConfig.speculative``.
"""

import logging
from typing import Any, Optional

from zookeeper_tpu.core import ComponentField, Field, component
from zookeeper_tpu.models.base import Model
from zookeeper_tpu.models.transformer import TransformerLM
from zookeeper_tpu.serving.decode.engine import DecodeEngine

logger = logging.getLogger(__name__)

__all__ = ["SpeculativeDecoding"]


@component
class SpeculativeDecoding:
    """Config + runtime binding for the draft/verify schedule.

    Fields are the ``LMServingConfig.speculative`` CLI surface
    (``speculative.enabled=True speculative.k=4
    speculative.draft_checkpoint=/ckpt/student``); :meth:`bind` attaches
    the runtime objects — a built draft module + weights and the
    TEACHER engine whose geometry the internal draft engine mirrors.
    """

    #: Master switch: False (default) = plain decode, the speculative
    #: machinery entirely dormant.
    enabled: bool = Field(False)
    #: Draft tokens proposed per window. Each window costs ``k`` draft
    #: dispatches + ONE teacher verify and emits between 1 and ``k + 1``
    #: tokens (acceptance-dependent), so the teacher dispatch rate drops
    #: by up to ``k + 1``x. Raise k when acceptance is high (draft
    #: closely agrees with the teacher), lower it when rejections waste
    #: draft work — docs/DESIGN.md §18 has the cost model.
    k: int = Field(4)
    #: Draft model geometry (built at the teacher's seq_len/vocab) —
    #: the distilled student's config, typically far smaller than the
    #: teacher. Used by ``LMServingConfig`` to build the draft module;
    #: programmatic callers pass a built module to :meth:`bind`.
    draft_model: Model = ComponentField(TransformerLM)
    #: ``save_model`` export / Checkpointer directory holding the draft
    #: weights (the distill pipeline's student export). None = fresh-
    #: init draft_model weights — program-shape smoke only (acceptance
    #: will be ~chance), flagged loudly at bind.
    draft_checkpoint: Optional[str] = Field(None)
    #: EMA-vs-raw selection for the draft checkpoint (same contract as
    #: the teacher's ``weights``).
    draft_weights: str = Field("auto")

    # -- binding ---------------------------------------------------------

    def bind(
        self,
        engine: DecodeEngine,
        draft_module: Any,
        draft_params: Any,
        draft_state: Any = None,
        *,
        partitioner: Any = None,
    ) -> "SpeculativeDecoding":
        """Attach the draft: builds + warms an internal
        :class:`DecodeEngine` over ``draft_module`` mirroring the
        TEACHER ``engine``'s slot/bucket/capacity geometry (so admission
        groups and slot ids map 1:1 and the draft cache shards through
        the same ``decode_cache_sharding`` seam), and pre-compiles the
        verify widths — the teacher's ``k + 1`` window and the draft's
        width-2 catch-up/append program. Raises ``ValueError`` on
        config bugs (bad k, vocab mismatch, draft positional table too
        short for the prompt buckets) — the loud half of the
        "degrade loudly" contract lives in ``LMServingConfig``."""
        from zookeeper_tpu.core import configure

        engine._require_bound()
        if int(self.k) < 1:
            raise ValueError(f"speculative.k={self.k} must be >= 1.")
        teacher_vocab = getattr(engine._module, "vocab_size", None)
        draft_vocab = getattr(draft_module, "vocab_size", None)
        if (
            teacher_vocab is not None
            and draft_vocab is not None
            and int(teacher_vocab) != int(draft_vocab)
        ):
            raise ValueError(
                f"draft vocab_size {draft_vocab} != teacher vocab_size "
                f"{teacher_vocab}: draft proposals would be scored "
                "against a different token id space — speculation would "
                "be silently meaningless. Build the draft at the "
                "teacher's vocabulary."
            )
        draft = DecodeEngine()
        configure(
            draft,
            {
                # Mirror the TEACHER geometry exactly: one admission
                # plan serves both caches, and the draft rides the same
                # mesh/sharding seam.
                "slots": int(engine.slots),
                "seq_buckets": tuple(engine._seq_buckets),
                "prefill_buckets": tuple(engine._prefill_buckets),
                "kv_capacity": int(engine.capacity),
                "page_size": int(engine.page_size),
                "decode_attention": str(engine.decode_attention),
                "ledger_prefix": "draft_",
            },
            name="speculative_draft_engine",
        )
        draft.bind(
            draft_module,
            draft_params,
            draft_state,
            partitioner=(
                partitioner if partitioner is not None
                else engine._partitioner
            ),
        )
        # Warm the full draft grid + both verify widths so the first
        # speculative window never waits on XLA and compile_count pins
        # at zero growth under traffic for BOTH engines.
        draft.warmup()
        draft.warmup_verify(2)  # catch-up gap (<=1) + current token
        engine.warmup_verify(int(self.k) + 1)
        object.__setattr__(self, "_engine", engine)
        object.__setattr__(self, "_draft_engine", draft)
        # Lifetime acceptance accounting (the /statusz + result-line
        # numbers; the metrics counters are the scrapeable twins).
        object.__setattr__(self, "_proposed_total", 0)
        object.__setattr__(self, "_accepted_total", 0)
        logger.info(
            "speculative decoding bound: k=%d, draft %s (%d layers), "
            "verify window %d",
            int(self.k),
            type(draft_module).__name__,
            int(getattr(draft_module, "num_layers", -1)),
            int(self.k) + 1,
        )
        return self

    def _require_bound(self) -> None:
        if getattr(self, "_draft_engine", None) is None:
            raise RuntimeError(
                "SpeculativeDecoding is not bound: call spec.bind("
                "teacher_engine, draft_module, draft_params) first."
            )

    # -- runtime surface (read by the scheduler) -------------------------

    @property
    def engine(self) -> DecodeEngine:
        """The teacher engine this binding mirrors."""
        self._require_bound()
        return self._engine

    @property
    def draft_engine(self) -> DecodeEngine:
        self._require_bound()
        return self._draft_engine

    @property
    def window(self) -> int:
        """Teacher verify width: ``k`` draft tokens + the current input
        token (all ``k + 1`` positions scored in one dispatch)."""
        return int(self.k) + 1

    def record_window(self, proposed: int, accepted: int) -> None:
        """Lifetime acceptance accounting (scheduler commit phase,
        called under the scheduler lock)."""
        object.__setattr__(
            self, "_proposed_total", self._proposed_total + int(proposed)
        )
        object.__setattr__(
            self, "_accepted_total", self._accepted_total + int(accepted)
        )

    @property
    def acceptance_rate(self) -> float:
        """Lifetime accepted-draft fraction (-1 before any window)."""
        proposed = getattr(self, "_proposed_total", 0)
        if not proposed:
            return -1.0
        return self._accepted_total / proposed

    def status(self) -> dict:
        """The ``/statusz`` ``speculative`` sub-section: k, live
        acceptance, and the draft engine's compile discipline."""
        self._require_bound()
        draft = self._draft_engine
        return {
            "enabled": True,
            "k": int(self.k),
            "acceptance_rate": round(self.acceptance_rate, 4),
            "proposed_tokens": int(self._proposed_total),
            "accepted_tokens": int(self._accepted_total),
            "draft_compiles": draft.compile_count,
            "draft_recompiles_detected": draft.recompiles_detected,
            "draft_decode_attention": draft.decode_attention_flavor,
        }
