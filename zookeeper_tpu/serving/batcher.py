"""Request coalescing: many ``submit()`` calls, one compiled dispatch.

The serving counterpart of the fused training loop's slab: per-request
dispatch pays Python + dispatch + readback once per REQUEST; the
``MicroBatcher`` pays it once per MICRO-BATCH by concatenating queued
requests (FIFO, row-granular) into the engine's largest bucket, padding
only the final remainder, and slicing per-request results back out of
the one readback.

Degradation contract (all paths pinned in tests/serving/test_batcher.py):

- *Oversized* requests (more rows than the largest bucket) are split
  across consecutive dispatches and re-assembled — callers never see
  the bucket limit.
- *Queue-full* applies backpressure instead of buffering toward OOM:
  synchronous mode drains the backlog inline; async mode blocks the
  submitter until the worker catches up.
- *Partial* micro-batches (queue drains below a bucket) pad up to the
  smallest covering bucket — never a fresh compile.

Determinism: inference is row-independent (engine docstring), so a
request's result is bit-identical however it was coalesced or split —
the batcher changes WHEN rows run, never WHAT they compute.

Threading: ``synchronous=True`` (the default) is completely thread- and
clock-free — requests queue until ``flush()`` (or ``result()``, which
flushes on demand), so tier-1 CPU tests are deterministic. Async mode
adds one worker thread that dispatches whenever the largest bucket
fills or the oldest request has waited ``max_delay_ms``.
"""

import threading
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from zookeeper_tpu.core import Field, component

Array = Any


class PendingResult:
    """Handle for one submitted request; ``result()`` yields the
    ``[n, ...]`` output rows in submission order."""

    __slots__ = (
        "_batcher", "_event", "_parts", "_rows", "_rows_done",
        "_value", "_error", "_done", "_t_submit",
    )

    def __init__(self, batcher: "MicroBatcher", rows: int, event) -> None:
        self._batcher = batcher
        self._event = event  # None in synchronous mode
        self._parts: List[np.ndarray] = []
        self._rows = rows
        self._rows_done = 0
        self._value = None
        self._error: Optional[BaseException] = None
        self._done = False
        self._t_submit = time.perf_counter()

    @property
    def done(self) -> bool:
        return self._done

    def _deliver(self, part: np.ndarray) -> None:
        """Called by the batcher with consecutive row slices (FIFO order
        guarantees they arrive in row order, including across the splits
        of an oversized request)."""
        self._parts.append(part)
        self._rows_done += part.shape[0]
        if self._rows_done >= self._rows:
            self._value = (
                self._parts[0]
                if len(self._parts) == 1
                else np.concatenate(self._parts)
            )
            self._parts = []
            self._finish()

    def _fail(self, error: BaseException) -> None:
        self._error = error
        self._finish()

    def _finish(self) -> None:
        self._done = True
        latency_ms = (time.perf_counter() - self._t_submit) * 1e3
        self._batcher._record_done(self, latency_ms)
        if self._event is not None:
            self._event.set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._done:
            if self._event is None:
                # Deterministic synchronous mode: asking for a result IS
                # the flush trigger — no threads, no clocks.
                self._batcher.flush()
            elif not self._event.wait(timeout):
                raise TimeoutError(
                    f"request not served within {timeout}s (worker "
                    "stalled, or close() was called before flush())."
                )
        if self._error is not None:
            raise self._error
        return self._value


@component
class MicroBatcher:
    """Coalescing request queue in front of an
    :class:`~zookeeper_tpu.serving.engine.InferenceEngine`."""

    #: Async mode: dispatch as soon as the largest bucket fills, or when
    #: the OLDEST pending request has waited this long — the knob trading
    #: p99 latency against bucket fill (docs/DESIGN.md §8). Ignored in
    #: synchronous mode (flush() is the trigger).
    max_delay_ms: float = Field(2.0)
    #: Backpressure threshold in ROWS. A submit that would grow the
    #: queue past this drains the backlog (sync) or blocks (async)
    #: rather than buffering unboundedly toward OOM.
    max_queue_rows: int = Field(4096)
    #: Thread- and clock-free deterministic mode (tier-1 default):
    #: requests queue until flush()/result().
    synchronous: bool = Field(True)

    # -- wiring ----------------------------------------------------------

    def bind(self, engine, metrics=None) -> "MicroBatcher":
        if self.max_queue_rows < 1:
            raise ValueError(
                f"max_queue_rows={self.max_queue_rows} must be >= 1."
            )
        if self.max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms={self.max_delay_ms} must be >= 0."
            )
        object.__setattr__(self, "_engine", engine)
        object.__setattr__(self, "_metrics", metrics)
        # Queue of (request, lo, hi): row slice [lo, hi) of request still
        # owed. Oversized/partially-taken requests stay at the head with
        # lo advanced, so delivery is always in row order.
        object.__setattr__(self, "_queue", [])
        object.__setattr__(self, "_queue_rows", 0)
        object.__setattr__(self, "_cv", threading.Condition())
        object.__setattr__(self, "_worker", None)
        object.__setattr__(self, "_inflight", False)
        object.__setattr__(self, "_stop", threading.Event())
        return self

    def _require_bound(self) -> None:
        if getattr(self, "_engine", None) is None:
            raise RuntimeError(
                "MicroBatcher is not bound: call batcher.bind(engine) "
                "before submit()."
            )

    def _record_done(self, req: PendingResult, latency_ms: float) -> None:
        if self._metrics is not None and req._error is None:
            self._metrics.record_request(latency_ms, req._rows)

    @property
    def queue_rows(self) -> int:
        return getattr(self, "_queue_rows", 0)

    # -- submission ------------------------------------------------------

    def submit(self, x: Array) -> PendingResult:
        """Enqueue one request ``[n, *input_shape]``; returns a
        :class:`PendingResult`. Never dispatches inline in async mode;
        in sync mode dispatch happens at flush()/result() (or right here
        when backpressure triggers)."""
        self._require_bound()
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(
                f"request must have at least one row, got shape {x.shape}."
            )
        n = int(x.shape[0])
        if self.synchronous:
            if self._queue and self._queue_rows + n > self.max_queue_rows:
                self.flush()  # backpressure: drain the backlog inline
            req = PendingResult(self, n, event=None)
            self._queue.append((req, x, 0, n))
            object.__setattr__(self, "_queue_rows", self._queue_rows + n)
            if self._metrics is not None:
                self._metrics.record_queue_depth(self._queue_rows)
            return req
        self._ensure_worker()
        req = PendingResult(self, n, event=threading.Event())
        with self._cv:
            while (
                self._queue
                and self._queue_rows + n > self.max_queue_rows
                and not self._stop.is_set()
            ):
                self._cv.wait(0.01)  # backpressure: block the submitter
            self._queue.append((req, x, 0, n))
            object.__setattr__(self, "_queue_rows", self._queue_rows + n)
            if self._metrics is not None:
                self._metrics.record_queue_depth(self._queue_rows)
            self._cv.notify_all()
        return req

    # -- dispatch planning ----------------------------------------------

    def _take_plan(self) -> List[Tuple[PendingResult, np.ndarray]]:
        """Pop up to ``engine.max_batch`` rows off the queue head
        (row-granular: the last request taken may contribute only a
        prefix, its remainder staying at the head). Caller holds the
        lock in async mode; sync mode is single-threaded."""
        room = self._engine.max_batch
        plan: List[Tuple[PendingResult, np.ndarray]] = []
        taken = 0
        while self._queue and taken < room:
            req, x, lo, hi = self._queue[0]
            take = min(room - taken, hi - lo)
            plan.append((req, x[lo : lo + take]))
            taken += take
            if lo + take == hi:
                self._queue.pop(0)
            else:
                self._queue[0] = (req, x, lo + take, hi)
        object.__setattr__(self, "_queue_rows", self._queue_rows - taken)
        return plan

    def _run_plan(self, plan: List[Tuple[PendingResult, np.ndarray]]) -> None:
        """One engine dispatch + ONE host readback for the whole
        micro-batch, then per-request slices delivered back out."""
        import jax

        rows = sum(part.shape[0] for _, part in plan)
        if rows == 0:
            return
        batch = (
            plan[0][1]
            if len(plan) == 1
            else np.concatenate([part for _, part in plan])
        )
        try:
            out = np.asarray(jax.device_get(self._engine.infer(batch)))
        except Exception as e:
            for req, _ in plan:
                req._fail(e)
            raise
        if self._metrics is not None:
            self._metrics.record_dispatch(rows, self._engine.bucket_for(rows))
        offset = 0
        for req, part in plan:
            k = part.shape[0]
            req._deliver(out[offset : offset + k])
            offset += k

    # -- synchronous drain ----------------------------------------------

    def flush(self) -> None:
        """Serve every queued request. In synchronous mode this is THE
        dispatch path (deterministic: FIFO micro-batches of at most
        ``engine.max_batch`` rows each); in async mode it blocks until
        the worker has drained the queue."""
        self._require_bound()
        if self.synchronous:
            while self._queue:
                self._run_plan(self._take_plan())
            return
        with self._cv:
            self._cv.notify_all()
            while (self._queue or self._inflight) and not self._stop.is_set():
                self._cv.wait(0.01)

    # -- async worker ----------------------------------------------------

    def _ensure_worker(self) -> None:
        if getattr(self, "_worker", None) is None:
            thread = threading.Thread(
                target=self._worker_loop, name="microbatcher", daemon=True
            )
            object.__setattr__(self, "_worker", thread)
            thread.start()

    def _worker_loop(self) -> None:
        max_batch = self._engine.max_batch
        delay_s = self.max_delay_ms / 1e3
        while not self._stop.is_set():
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    self._cv.wait(0.05)
                if self._stop.is_set():
                    break
                # Coalescing window: go when the largest bucket fills or
                # the oldest request has waited max_delay_ms.
                oldest = self._queue[0][0]._t_submit
                while (
                    self._queue_rows < max_batch
                    and not self._stop.is_set()
                ):
                    remaining = oldest + delay_s - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                plan = self._take_plan()
                object.__setattr__(self, "_inflight", True)
            try:
                self._run_plan(plan)
            except Exception:
                pass  # requests carry the error; the worker must survive
            finally:
                with self._cv:
                    object.__setattr__(self, "_inflight", False)
                    self._cv.notify_all()

    def close(self) -> None:
        """Stop the async worker (pending requests are failed so no
        result() blocks forever). Safe to call repeatedly / unbound."""
        if getattr(self, "_engine", None) is None:
            return
        self._stop.set()
        worker = getattr(self, "_worker", None)
        if worker is not None:
            with self._cv:
                self._cv.notify_all()
            worker.join(timeout=5)
            object.__setattr__(self, "_worker", None)
        err = RuntimeError("MicroBatcher closed with requests pending.")
        for req, _, _, _ in self._queue:
            if not req.done:
                req._fail(err)
        del self._queue[:]
        object.__setattr__(self, "_queue_rows", 0)
        self._stop.clear()
