"""Request coalescing: many ``submit()`` calls, one compiled dispatch.

The serving counterpart of the fused training loop's slab: per-request
dispatch pays Python + dispatch + readback once per REQUEST; the
``MicroBatcher`` pays it once per MICRO-BATCH by concatenating queued
requests (FIFO, row-granular) into the engine's largest bucket, padding
only the final remainder, and slicing per-request results back out of
the one readback.

Degradation contract (all paths pinned in tests/serving/):

- *Oversized* requests (more rows than the largest bucket) are split
  across consecutive dispatches and re-assembled — callers never see
  the bucket limit.
- *Queue-full* applies backpressure instead of buffering toward OOM:
  synchronous mode drains the backlog inline; async mode blocks the
  submitter until the worker catches up.
- *Overload shedding* (``shed_above_rows > 0``): instead of blocking
  submitters, a submit that would push the queue past the threshold
  raises :class:`RejectedError` immediately — the load-shed posture a
  user-facing service wants (fail fast, let the client retry elsewhere)
  vs the backpressure posture a batch pipeline wants.
- *Deadlines*: a request may carry ``deadline_ms``; expired requests
  are failed with :class:`DeadlineExpiredError` (never dispatched, and
  ``result()`` NEVER blocks past the deadline — the serving-resilience
  acceptance pin).
- *Worker death*: if the async worker thread dies (bug, injected
  crash), every queued and in-flight request is failed cleanly with
  :class:`WorkerCrashedError` — no ``result()`` hangs — and the next
  ``submit()`` starts a fresh worker (``ServingMetrics`` counts
  ``worker_restarts``).
- *Partial* micro-batches (queue drains below a bucket) pad up to the
  smallest covering bucket — never a fresh compile.

Determinism: inference is row-independent (engine docstring), so a
request's result is bit-identical however it was coalesced or split —
the batcher changes WHEN rows run, never WHAT they compute.

Threading: ``synchronous=True`` (the default) is completely thread- and
clock-free — requests queue until ``flush()`` (or ``result()``, which
flushes on demand), so tier-1 CPU tests are deterministic (deadline
tests use ``deadline_ms=0``, which is expiry-by-construction, not
timing). Async mode adds one worker thread that dispatches whenever the
largest bucket fills or the oldest request has waited ``max_delay_ms``.
"""

import logging
import threading
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.observability import recorder as _recorder
from zookeeper_tpu.observability import trace as _trace
from zookeeper_tpu.observability.requests import RequestLog, next_rid

Array = Any

logger = logging.getLogger(__name__)


class RejectedError(RuntimeError):
    """Load shedding: the queue is past ``shed_above_rows``; the request
    was never enqueued. Clients should back off / retry elsewhere."""


class DeadlineExpiredError(TimeoutError):
    """The request's ``deadline_ms`` elapsed before its rows were
    served; it has been failed (dropped from the queue if still
    pending). A ``TimeoutError`` subclass so generic timeout handling
    catches it."""


class WorkerCrashedError(RuntimeError):
    """The async worker thread died with this request queued or in
    flight. The request was failed (not silently dropped); submitting
    again runs on a freshly restarted worker."""


def outcome_of(error: Optional[BaseException]) -> str:
    """Map a terminal error to the RequestLog outcome taxonomy
    (``observability.requests.OUTCOMES``) — shared by the batcher and
    the decode scheduler so the two services' summaries read the
    same."""
    if error is None:
        return "ok"
    if isinstance(error, DeadlineExpiredError):
        return "deadline_expired"
    if isinstance(error, WorkerCrashedError):
        return "crashed"
    if isinstance(error, RejectedError):
        return "shed"
    return "error"


class PendingResult:
    """Handle for one submitted request; ``result()`` yields the
    ``[n, ...]`` output rows in submission order. Carries the request's
    ``rid`` (minted at submit, docs/DESIGN.md §16) so its trace records
    link up as one flow and its terminal summary lands in the
    batcher's ``RequestLog``."""

    __slots__ = (
        "_batcher", "_event", "_parts", "_rows", "_rows_done",
        "_value", "_error", "_done", "_t_submit", "_deadline_at",
        "_lock", "rid", "_t_dispatch_ns", "_bucket",
    )

    def __init__(
        self,
        batcher: "MicroBatcher",
        rows: int,
        event,
        deadline_at: Optional[float] = None,
        rid: Optional[int] = None,
    ) -> None:
        self._batcher = batcher
        self._event = event  # None in synchronous mode
        self._parts: List[np.ndarray] = []
        self._rows = rows
        self._rows_done = 0
        self._value = None
        self._error: Optional[BaseException] = None
        self._done = False
        self._t_submit = time.perf_counter()
        self._deadline_at = deadline_at  # absolute perf_counter secs
        #: Request id (process-monotonic; None only for handles built
        #: outside submit(), e.g. direct construction in tests).
        self.rid = rid
        self._t_dispatch_ns: Optional[int] = None
        self._bucket: Optional[int] = None
        # Completion can race between the worker (deliver), a crash
        # handler (fail), and the caller's deadline expiry (fail):
        # first transition wins, the rest are no-ops.
        self._lock = threading.Lock()

    @property
    def done(self) -> bool:
        return self._done

    def expired(self, now: Optional[float] = None) -> bool:
        """Whether the deadline has passed (False when none was set)."""
        if self._deadline_at is None:
            return False
        return (time.perf_counter() if now is None else now) >= self._deadline_at

    def _deliver(self, part: np.ndarray) -> None:
        """Called by the batcher with consecutive row slices (FIFO order
        guarantees they arrive in row order, including across the splits
        of an oversized request). A no-op once the request completed
        (e.g. already failed on deadline expiry)."""
        with self._lock:
            if self._done:
                return
            self._parts.append(part)
            self._rows_done += part.shape[0]
            if self._rows_done >= self._rows:
                self._value = (
                    self._parts[0]
                    if len(self._parts) == 1
                    else np.concatenate(self._parts)
                )
                self._parts = []
                self._finish()

    def _fail(self, error: BaseException) -> bool:
        """Fail the request; returns True only for the thread that
        actually performed the transition (completion is first-wins)."""
        with self._lock:
            if self._done:
                return False
            self._error = error
            self._finish()
            return True

    def _finish(self) -> None:
        """Caller holds ``_lock``."""
        self._done = True
        latency_ms = (time.perf_counter() - self._t_submit) * 1e3
        self._batcher._record_done(self, latency_ms)
        if self._event is not None:
            self._event.set()

    def _expire(self) -> None:
        """Fail on deadline expiry (idempotent: concurrent expirers —
        the worker's queue sweep and the caller's result() timeout —
        count the metric exactly once, decided by the locked
        transition)."""
        if self._fail(
            DeadlineExpiredError(
                f"request deadline expired after "
                f"{(time.perf_counter() - self._t_submit) * 1e3:.1f}ms "
                "(queue wait exceeded deadline_ms)"
            )
        ):
            self._batcher._record_deadline_expired()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block for the rows (async mode) or flush-and-return (sync
        mode). NEVER blocks past the request's deadline: on expiry the
        request fails with :class:`DeadlineExpiredError` even if the
        worker is stalled or dead."""
        if not self._done:
            if self._event is None:
                # Deterministic synchronous mode: asking for a result IS
                # the flush trigger — no threads, no clocks.
                self._batcher.flush()
                if not self._done and self.expired():
                    self._expire()
            else:
                wait_s = timeout
                if self._deadline_at is not None:
                    remaining = self._deadline_at - time.perf_counter()
                    wait_s = (
                        remaining
                        if timeout is None
                        else min(timeout, remaining)
                    )
                if not self._event.wait(max(0.0, wait_s) if wait_s is not None else None):
                    if self.expired():
                        self._expire()
                    else:
                        raise TimeoutError(
                            f"request not served within {timeout}s (worker "
                            "stalled, or close() was called before flush())."
                        )
        if self._error is not None:
            raise self._error
        return self._value


@component
class MicroBatcher:
    """Coalescing request queue in front of an
    :class:`~zookeeper_tpu.serving.engine.InferenceEngine`."""

    #: Async mode: dispatch as soon as the largest bucket fills, or when
    #: the OLDEST pending request has waited this long — the knob trading
    #: p99 latency against bucket fill (docs/DESIGN.md §8). Ignored in
    #: synchronous mode (flush() is the trigger).
    max_delay_ms: float = Field(2.0)
    #: Backpressure threshold in ROWS. A submit that would grow the
    #: queue past this drains the backlog (sync) or blocks (async)
    #: rather than buffering unboundedly toward OOM.
    max_queue_rows: int = Field(4096)
    #: Load shedding threshold in ROWS (0 = off). When on, a submit that
    #: would grow the queue past this raises :class:`RejectedError`
    #: instead of blocking/buffering — overload fails fast (the
    #: ``ServingMetrics.rejected`` counter tracks the shed rate).
    #: Checked BEFORE backpressure; an empty queue always admits one
    #: request (oversized requests stay servable).
    shed_above_rows: int = Field(0)
    #: Default per-request deadline in ms (0 = none). ``submit()``'s
    #: ``deadline_ms`` overrides per request. Expired requests fail with
    #: :class:`DeadlineExpiredError` — at dispatch planning (never
    #: served late) and in ``result()`` (never blocks past it).
    default_deadline_ms: float = Field(0.0)
    #: Thread- and clock-free deterministic mode (tier-1 default):
    #: requests queue until flush()/result().
    synchronous: bool = Field(True)

    # -- wiring ----------------------------------------------------------

    def bind(
        self, engine, metrics=None, request_log=None, guard=None
    ) -> "MicroBatcher":
        if self.max_queue_rows < 1:
            raise ValueError(
                f"max_queue_rows={self.max_queue_rows} must be >= 1."
            )
        if self.max_delay_ms < 0:
            raise ValueError(
                f"max_delay_ms={self.max_delay_ms} must be >= 0."
            )
        if self.shed_above_rows < 0 or self.default_deadline_ms < 0:
            raise ValueError(
                f"shed_above_rows={self.shed_above_rows} and "
                f"default_deadline_ms={self.default_deadline_ms} must be "
                ">= 0 (0 disables)."
            )
        object.__setattr__(self, "_engine", engine)
        object.__setattr__(self, "_metrics", metrics)
        # Optional OverloadGuard (docs/DESIGN.md §24): predictive
        # admission on TOP of the static shed_above_rows threshold.
        object.__setattr__(self, "_guard", guard)
        # Per-service terminal-request ring (docs/DESIGN.md §16): one
        # compact summary per request that reached an outcome, exposed
        # at /statusz and dumped into flight-recorder bundles.
        object.__setattr__(
            self,
            "_request_log",
            request_log if request_log is not None else RequestLog("serving"),
        )
        # Queue of (request, x, lo, hi): row slice [lo, hi) of request
        # still owed. Oversized/partially-taken requests stay at the
        # head with lo advanced, so delivery is always in row order.
        object.__setattr__(self, "_queue", [])
        object.__setattr__(self, "_queue_rows", 0)
        object.__setattr__(self, "_cv", threading.Condition())
        object.__setattr__(self, "_worker", None)
        object.__setattr__(self, "_inflight", False)
        object.__setattr__(self, "_plan_inflight", None)
        object.__setattr__(self, "_force_drain", False)
        object.__setattr__(self, "_stop", threading.Event())
        return self

    def _require_bound(self) -> None:
        if getattr(self, "_engine", None) is None:
            raise RuntimeError(
                "MicroBatcher is not bound: call batcher.bind(engine) "
                "before submit()."
            )

    def _weights_step(self) -> Optional[int]:
        return (
            self._metrics.weights_step if self._metrics is not None else None
        )

    def _record_done(self, req: PendingResult, latency_ms: float) -> None:
        outcome = outcome_of(req._error)
        if _trace.enabled():
            _trace.event(
                "request_complete",
                rid=req.rid,
                attrs={
                    "rows": req._rows,
                    "latency_ms": round(latency_ms, 3),
                    "error": type(req._error).__name__
                    if req._error is not None
                    else None,
                },
            )
        if req.rid is not None:
            self._request_log.append(
                req.rid,
                outcome,
                enqueue_ns=int(req._t_submit * 1e9),
                dispatch_ns=req._t_dispatch_ns,
                complete_ns=time.perf_counter_ns(),
                rows=req._rows,
                bucket=req._bucket,
                weights_step=self._weights_step(),
                detail=(
                    type(req._error).__name__
                    if req._error is not None
                    else None
                ),
            )
        if self._metrics is not None and req._error is None:
            self._metrics.record_request(latency_ms, req._rows)
        guard = getattr(self, "_guard", None)
        if (
            guard is not None
            and guard.enabled
            and req._error is None
            and req._t_dispatch_ns is not None
        ):
            # Feed the admission estimator from OBSERVED outcomes:
            # service = dispatch→complete per row, wait = submit→
            # dispatch. Only successes — a crashed/expired request's
            # timings would teach the EWMA the failure mode, not the
            # service rate.
            now_ns = time.perf_counter_ns()
            guard.observe_service(
                (now_ns - req._t_dispatch_ns) / 1e6, max(1, req._rows)
            )
            guard.observe_wait(
                (req._t_dispatch_ns - req._t_submit * 1e9) / 1e6
            )

    def _record_deadline_expired(self) -> None:
        _trace.event("request_deadline_expired")
        if self._metrics is not None:
            self._metrics.record_deadline_expired()

    @property
    def queue_rows(self) -> int:
        return getattr(self, "_queue_rows", 0)

    @property
    def request_log(self) -> Optional[RequestLog]:
        """This batcher's terminal-request ring (None before bind)."""
        return getattr(self, "_request_log", None)

    # -- submission ------------------------------------------------------

    def _deadline_at(self, deadline_ms: Optional[float]) -> Optional[float]:
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms or None
        if deadline_ms is None:
            return None
        if deadline_ms < 0:
            raise ValueError(f"deadline_ms={deadline_ms} must be >= 0.")
        return time.perf_counter() + deadline_ms / 1e3

    def _shed_check(self, n: int, rid: Optional[int] = None) -> None:
        """Raise ``RejectedError`` when admitting ``n`` more rows would
        pass the shed threshold (caller holds the lock in async mode)."""
        if (
            self.shed_above_rows > 0
            and self._queue
            and self._queue_rows + n > self.shed_above_rows
        ):
            if self._metrics is not None:
                self._metrics.record_rejected()
            if _trace.enabled():
                _trace.event(
                    "request_shed",
                    rid=rid,
                    attrs={"rows": n, "queue_rows": self._queue_rows},
                )
            if rid is not None:
                # The one terminal path with no PendingResult: the
                # request was never enqueued, so its summary lands
                # here.
                now_ns = time.perf_counter_ns()
                self._request_log.append(
                    rid,
                    "shed",
                    enqueue_ns=now_ns,
                    complete_ns=now_ns,
                    rows=n,
                    weights_step=self._weights_step(),
                )
            raise RejectedError(
                f"queue at {self._queue_rows} rows; admitting {n} more "
                f"would exceed shed_above_rows={self.shed_above_rows} — "
                "request shed (service overloaded, retry with backoff)."
            )

    def _guard_check(
        self, n: int, rid: int, deadline_at: Optional[float]
    ) -> None:
        """Predicted-miss admission (docs/DESIGN.md §24): shed when the
        guard's EWMA-based completion estimate says this request cannot
        meet its deadline given the CURRENT queue. Runs after the static
        row-count check; same empty-queue invariant (the guard never
        sheds when nothing is queued ahead). Caller holds the lock in
        async mode."""
        guard = getattr(self, "_guard", None)
        if guard is None or not guard.enabled:
            return
        # Deferred: guardrails imports RejectedError from this module.
        from zookeeper_tpu.serving.guardrails import PredictedMissError
        deadline_ms = (
            (deadline_at - time.perf_counter()) * 1e3
            if deadline_at is not None
            else None
        )
        ok, predicted = guard.admit(
            queued_units=self._queue_rows,
            request_units=n,
            deadline_ms=deadline_ms,
        )
        if ok:
            return
        if self._metrics is not None:
            self._metrics.record_rejected()
        if _trace.enabled():
            _trace.event(
                "request_shed",
                rid=rid,
                attrs={
                    "rows": n,
                    "queue_rows": self._queue_rows,
                    "reason": "predicted_miss",
                    "predicted_ms": round(predicted, 3),
                },
            )
        now_ns = time.perf_counter_ns()
        self._request_log.append(
            rid,
            "shed",
            enqueue_ns=now_ns,
            complete_ns=now_ns,
            rows=n,
            weights_step=self._weights_step(),
            detail=f"PredictedMissError predicted_ms={predicted:.1f}",
        )
        raise PredictedMissError(
            f"predicted completion in {predicted:.1f}ms exceeds the "
            f"{deadline_ms:.1f}ms deadline with {self._queue_rows} rows "
            "queued — shed at admission rather than served late."
        )

    def submit(
        self, x: Array, *, deadline_ms: Optional[float] = None
    ) -> PendingResult:
        """Enqueue one request ``[n, *input_shape]``; returns a
        :class:`PendingResult`. Never dispatches inline in async mode;
        in sync mode dispatch happens at flush()/result() (or right here
        when backpressure triggers). ``deadline_ms`` bounds how long the
        request may wait: ``None`` falls back to the component's
        ``default_deadline_ms`` (whose 0 means "no deadline"), while an
        EXPLICIT ``deadline_ms=0`` is already-expired — the
        deterministic clock-free expiry the chaos tests use. Raises
        :class:`RejectedError` without enqueueing when load shedding is
        on and the queue is past the threshold."""
        self._require_bound()
        x = np.asarray(x)
        if x.ndim < 1 or x.shape[0] < 1:
            raise ValueError(
                f"request must have at least one row, got shape {x.shape}."
            )
        n = int(x.shape[0])
        deadline_at = self._deadline_at(deadline_ms)
        # The rid is minted HERE — before shed/backpressure — so every
        # outcome (including a shed that never enqueues) is traceable
        # and RequestLog-recorded under one id (docs/DESIGN.md §16).
        rid = next_rid()
        if self.synchronous:
            self._shed_check(n, rid)
            self._guard_check(n, rid, deadline_at)
            if self._queue and self._queue_rows + n > self.max_queue_rows:
                self.flush()  # backpressure: drain the backlog inline
            req = PendingResult(
                self, n, event=None, deadline_at=deadline_at, rid=rid
            )
            self._queue.append((req, x, 0, n))
            object.__setattr__(self, "_queue_rows", self._queue_rows + n)
            if self._metrics is not None:
                self._metrics.record_queue_depth(self._queue_rows)
            if _trace.enabled():
                _trace.event(
                    "request_enqueue",
                    rid=rid,
                    attrs={"rows": n, "queue_rows": self._queue_rows},
                )
            return req
        req = PendingResult(
            self, n, event=threading.Event(), deadline_at=deadline_at,
            rid=rid,
        )
        with self._cv:
            self._shed_check(n, rid)
            self._guard_check(n, rid, deadline_at)
            while (
                self._queue
                and self._queue_rows + n > self.max_queue_rows
                and not self._stop.is_set()
            ):
                self._cv.wait(0.01)  # backpressure: block the submitter
            self._queue.append((req, x, 0, n))
            object.__setattr__(self, "_queue_rows", self._queue_rows + n)
            if self._metrics is not None:
                self._metrics.record_queue_depth(self._queue_rows)
            if _trace.enabled():
                _trace.event(
                    "request_enqueue",
                    rid=rid,
                    attrs={"rows": n, "queue_rows": self._queue_rows},
                )
            # Worker liveness is checked UNDER the lock, after the
            # request is queued: _on_worker_crash also holds the lock,
            # so either cleanup already ran (dead worker observed here,
            # fresh one spawned) or it runs after us and fails THIS
            # request cleanly — a request can never land in the queue
            # with no worker and no failure (the hang this lock order
            # exists to prevent). The fresh thread blocks on the lock
            # until we release; no deadlock.
            self._ensure_worker()
            self._cv.notify_all()
        return req

    # -- dispatch planning ----------------------------------------------

    def _expire_overdue(self) -> None:
        """Fail-and-drop queued requests whose deadline has passed —
        they must never be dispatched late. Caller holds the lock in
        async mode; sync mode is single-threaded."""
        now = time.perf_counter()
        if not any(req.expired(now) for req, _, _, _ in self._queue):
            return
        kept = []
        dropped_rows = 0
        for entry in self._queue:
            req, _, lo, hi = entry
            if req.expired(now):
                dropped_rows += hi - lo
                req._expire()
            else:
                kept.append(entry)
        self._queue[:] = kept
        object.__setattr__(
            self, "_queue_rows", self._queue_rows - dropped_rows
        )

    def _take_plan(self) -> List[Tuple[PendingResult, np.ndarray]]:
        """Pop up to ``engine.max_batch`` rows off the queue head
        (row-granular: the last request taken may contribute only a
        prefix, its remainder staying at the head). Caller holds the
        lock in async mode; sync mode is single-threaded."""
        self._expire_overdue()
        room = self._engine.max_batch
        plan: List[Tuple[PendingResult, np.ndarray]] = []
        taken = 0
        while self._queue and taken < room:
            req, x, lo, hi = self._queue[0]
            take = min(room - taken, hi - lo)
            plan.append((req, x[lo : lo + take]))
            taken += take
            if lo + take == hi:
                self._queue.pop(0)
            else:
                self._queue[0] = (req, x, lo + take, hi)
        object.__setattr__(self, "_queue_rows", self._queue_rows - taken)
        return plan

    def _run_plan(self, plan: List[Tuple[PendingResult, np.ndarray]]) -> None:
        """One engine dispatch + ONE host readback for the whole
        micro-batch, then per-request slices delivered back out."""
        import jax

        rows = sum(part.shape[0] for _, part in plan)
        if rows == 0:
            return
        # Coalescing visibility: one span covers concat + engine
        # dispatch + the single host readback; the per-request
        # complete events that follow nest under it on the timeline.
        dispatch_span = _trace.span(
            "serve_dispatch",
            attrs=(
                {"rows": rows, "requests": len(plan)}
                if _trace.enabled()
                else None
            ),
        )
        try:
            with dispatch_span:
                t0 = time.perf_counter()
                t0_ns = time.perf_counter_ns()
                batch = (
                    plan[0][1]
                    if len(plan) == 1
                    else np.concatenate([part for _, part in plan])
                )
                bucket = self._engine.bucket_for(rows)
                # First-dispatch attribution BEFORE the device work: a
                # crash mid-infer still leaves the summary saying the
                # request reached dispatch. The per-rid instants sit
                # INSIDE this span, so the exporter's flow arrows bind
                # submit -> this dispatch slice.
                for req, _ in plan:
                    if req._t_dispatch_ns is None:
                        req._t_dispatch_ns = t0_ns
                        req._bucket = bucket
                    if _trace.enabled() and req.rid is not None:
                        _trace.event(
                            "request_dispatch",
                            rid=req.rid,
                            attrs={"bucket": bucket},
                        )
                out = np.asarray(jax.device_get(self._engine.infer(batch)))
                dispatch_s = time.perf_counter() - t0
            # The device_get above bounds the dispatch honestly: feed
            # the engine's serve watchdog + live MFU gauge (a no-op
            # for engine doubles in tests that don't implement it).
            # Suppressed: the inference already succeeded — a metrics
            # failure must not fail the plan's requests.
            observe = getattr(self._engine, "observe_dispatch", None)
            if observe is not None:
                try:
                    observe(rows, dispatch_s)
                except Exception:
                    logger.warning(
                        "observe_dispatch failed", exc_info=True
                    )
            if self._metrics is not None:
                self._metrics.record_dispatch(rows, bucket)
            offset = 0
            for req, part in plan:
                k = part.shape[0]
                req._deliver(out[offset : offset + k])
                offset += k
        except Exception as e:
            # The WHOLE dispatch path is covered, not just infer: a
            # failure after the rows were popped from the queue
            # (metrics, delivery) must still fail every request in the
            # plan — an undelivered-and-unfailed handle would hang
            # result() forever. _fail no-ops on already-delivered ones.
            for req, _ in plan:
                req._fail(e)
            raise

    # -- synchronous drain ----------------------------------------------

    def flush(self) -> None:
        """Serve every queued request. In synchronous mode this is THE
        dispatch path (deterministic: FIFO micro-batches of at most
        ``engine.max_batch`` rows each); in async mode it blocks until
        the worker has drained the queue (returning early — with the
        queue already failed clean — if the worker dies)."""
        self._require_bound()
        if self.synchronous:
            while self._queue:
                plan = self._take_plan()
                if plan:
                    self._run_plan(plan)
            return
        with self._cv:
            # Force-drain: the worker skips the remaining coalescing
            # window (flush means "serve NOW", however long max_delay_ms
            # had left).
            object.__setattr__(self, "_force_drain", True)
            self._cv.notify_all()
            try:
                while (
                    self._queue or self._inflight
                ) and not self._stop.is_set():
                    worker = getattr(self, "_worker", None)
                    if worker is None or not worker.is_alive():
                        break  # worker died; crash cleanup fails the queue
                    self._cv.wait(0.01)
            finally:
                object.__setattr__(self, "_force_drain", False)

    # -- async worker ----------------------------------------------------

    def _ensure_worker(self) -> None:
        worker = getattr(self, "_worker", None)
        if worker is None or not worker.is_alive():
            thread = threading.Thread(
                target=self._worker_loop, name="zk-microbatcher", daemon=True
            )
            object.__setattr__(self, "_worker", thread)
            thread.start()

    def _worker_loop(self) -> None:
        try:
            self._worker_body()
        except BaseException as e:
            # Worker death is survivable BY DESIGN: every queued and
            # in-flight request fails cleanly (no result() ever hangs
            # on a dead worker) and the next submit() restarts.
            self._on_worker_crash(e)

    def _worker_body(self) -> None:
        from zookeeper_tpu.resilience import faults

        max_batch = self._engine.max_batch
        delay_s = self.max_delay_ms / 1e3
        while not self._stop.is_set():
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    self._cv.wait(0.05)
                if self._stop.is_set():
                    break
                plan_fault = faults.active()
                if plan_fault is not None and plan_fault.take_worker_crash():
                    raise WorkerCrashedError(
                        "injected worker crash "
                        "(FaultPlan.serving_worker_crash)"
                    )
                # Coalescing window: go when the largest bucket fills or
                # the oldest request has waited max_delay_ms.
                oldest = self._queue[0][0]._t_submit
                while (
                    self._queue_rows < max_batch
                    and not self._stop.is_set()
                    and not self._force_drain
                ):
                    remaining = oldest + delay_s - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cv.wait(remaining)
                plan = self._take_plan()
                object.__setattr__(self, "_inflight", True)
                object.__setattr__(self, "_plan_inflight", plan)
            try:
                self._run_plan(plan)
            except Exception:
                pass  # requests carry the error; the worker must survive
            finally:
                with self._cv:
                    object.__setattr__(self, "_inflight", False)
                    object.__setattr__(self, "_plan_inflight", None)
                    self._cv.notify_all()

    def _on_worker_crash(self, error: BaseException) -> None:
        with self._cv:
            pending = [req for req, _, _, _ in self._queue]
            inflight = [
                req
                for req, _ in (getattr(self, "_plan_inflight", None) or [])
            ]
            del self._queue[:]
            object.__setattr__(self, "_queue_rows", 0)
            object.__setattr__(self, "_inflight", False)
            object.__setattr__(self, "_plan_inflight", None)
            # next submit()'s _ensure_worker starts a fresh thread
            object.__setattr__(self, "_worker", None)
            _trace.event(
                "worker_crash",
                attrs={
                    "error": type(error).__name__,
                    "failed_requests": len(inflight) + len(pending),
                },
            )
            if self._metrics is not None:
                self._metrics.record_worker_restart()
            wrapped = WorkerCrashedError(
                f"MicroBatcher worker crashed ({error!r}); this request "
                "was failed cleanly — resubmit to run on the restarted "
                "worker."
            )
            wrapped.__cause__ = error
            for req in inflight + pending:
                req._fail(wrapped)
            self._cv.notify_all()
        # Flight-recorder trigger (docs/DESIGN.md §16), fired AFTER the
        # fails so the bundle's RequestLog tail already carries the
        # crashed requests' outcome=crashed summaries alongside their
        # flow events — and OUTSIDE the lock, so a synchronous bundle
        # write (disk IO) never stalls concurrent submitters waiting on
        # _cv. notify() is one global read when no recorder is
        # installed and never raises into this cleanup path.
        _recorder.notify(
            "worker_crash",
            attrs={
                "error": type(error).__name__,
                "failed_requests": len(inflight) + len(pending),
            },
        )

    def close(self, drain: bool = False) -> None:
        """Stop the async worker. ``drain=True`` serves everything still
        queued first (a graceful shutdown); otherwise pending requests
        are FAILED so no ``result()`` blocks forever. Safe to call
        repeatedly / unbound."""
        if getattr(self, "_engine", None) is None:
            return
        if drain:
            try:
                self.flush()
            except Exception:
                pass  # per-request errors already delivered to handles
        self._stop.set()
        worker = getattr(self, "_worker", None)
        if worker is not None:
            with self._cv:
                self._cv.notify_all()
            worker.join(timeout=5)
            object.__setattr__(self, "_worker", None)
        err = RuntimeError("MicroBatcher closed with requests pending.")
        for req, _, _, _ in self._queue:
            req._fail(err)
        del self._queue[:]
        object.__setattr__(self, "_queue_rows", 0)
        self._stop.clear()
