"""Serving subsystem: dynamic-batching compiled inference.

The inference half of the north star (ROADMAP: "serves heavy traffic
from millions of users"): turn an exported or in-memory model into a
low-latency, high-throughput service by amortizing per-request Python
and dispatch cost the same way the fused training loop amortizes
per-step cost — many requests ride one compiled program.

- :class:`InferenceEngine` (``engine.py``): compiles one donation-safe,
  sharded forward per SHAPE BUCKET (padded batch sizes, plus sequence
  buckets for token models), with an explicit ``warmup()`` and a compile
  cache keyed on (bucket, dtype, mesh) so steady-state serving never
  recompiles.
- :class:`MicroBatcher` (``batcher.py``): coalesces concurrent
  ``submit()`` calls into the largest bucket that fills within
  ``max_delay_ms``, pads the remainder, slices per-request results back
  out; oversized requests split, a full queue applies backpressure (or
  sheds with :class:`RejectedError` past ``shed_above_rows``), requests
  carry deadlines (:class:`DeadlineExpiredError` — ``result()`` never
  blocks past one), a dead async worker fails its requests cleanly
  (:class:`WorkerCrashedError`) and restarts, and a deterministic
  synchronous mode keeps tier-1 tests thread-free.
- :class:`ServingMetrics` (``metrics.py``): request latency percentiles,
  queue depth, bucket-fill ratio, padding waste — emitted through the
  training ``MetricsWriter`` family.
- :class:`ServingConfig` (``service.py``): the ``Component`` tying model
  + checkpoint (EMA-vs-raw weight selection) + engine + batcher +
  metrics into one CLI-drivable task tree.
- ``zookeeper_tpu.serving.decode``: the autoregressive token-streaming
  half — paged/ring KV-cache :class:`DecodeEngine` (bucketed prefill +
  single decode-step compiled programs), slot-refill continuous
  batching in :class:`DecodeScheduler` (``generate()`` streaming API,
  deadlines/shedding/crash recovery, drain-boundary weight hot-swap),
  ``zk_decode_*`` metrics, and the :class:`LMServingConfig` CLI task
  (docs/DESIGN.md §15) — plus :class:`SpeculativeDecoding`, the
  draft/verify schedule that amortizes one teacher dispatch over a
  k+1-token window, certified token-identical to plain greedy decode
  (docs/DESIGN.md §18).
- ``zookeeper_tpu.serving.disagg``: disaggregated prefill/decode
  serving — one checkpoint bound into a prefill role and a decode role
  on two mesh slices (:class:`DisaggPartitioner`), completed prefills
  streaming their KV pool pages across via :class:`PageTransfer` under
  the :class:`DisaggScheduler`'s atomic refcount custody; certified
  token-identical to the single-mesh engine (docs/DESIGN.md §22).
- ``zookeeper_tpu.serving.fleet``: fleet serving — a
  :class:`FleetRouter` over N replica processes with prefix-affinity
  scheduling (one pageless
  :class:`~zookeeper_tpu.serving.decode.prefix_key.PrefixIndex` per
  replica, sharing the radix cache's EXACT chunk keying), session KV
  pinning, load fallback from live ``/metrics``, health-probed
  replicas with clean :class:`WorkerCrashedError` failure + cold
  re-route, and cross-process rid propagation (docs/DESIGN.md §23).
- ``zookeeper_tpu.serving.guardrails``: overload defenses —
  :class:`OverloadGuard` predicted-miss admission (EWMA queue-wait +
  per-token service estimate vs each request's deadline ⇒
  :class:`PredictedMissError` at submit instead of serving a request
  late), per-replica :class:`CircuitBreaker` in the fleet router
  (closed→open→half-open jittered probe→closed; slow-but-alive
  replicas excluded from routing), bounded rid-preserving retry of
  requests that fail before first token, and :class:`BrownOut`
  degraded mode applied only at the drained-slot-array boundary
  (docs/DESIGN.md §24). Judged by the ``zookeeper_tpu.loadgen``
  trace-replay harness.
"""

from zookeeper_tpu.serving.batcher import (
    DeadlineExpiredError,
    MicroBatcher,
    PendingResult,
    RejectedError,
    WorkerCrashedError,
)
from zookeeper_tpu.serving.decode import (
    DecodeEngine,
    DecodeMetrics,
    DecodeScheduler,
    DecodeStream,
    LMServingConfig,
    SpeculativeDecoding,
)
from zookeeper_tpu.serving.disagg import (
    DisaggPartitioner,
    DisaggScheduler,
    DisaggServingConfig,
    PageTransfer,
    PageTransferError,
)
from zookeeper_tpu.serving.engine import CheckpointWatcher, InferenceEngine
from zookeeper_tpu.serving.fleet import (
    FleetMetrics,
    FleetResponse,
    FleetRouter,
    FleetUnavailableError,
    ReplicaHandle,
)
from zookeeper_tpu.serving.guardrails import (
    BrownOut,
    CircuitBreaker,
    OverloadGuard,
    PredictedMissError,
)
from zookeeper_tpu.serving.metrics import ServingMetrics
from zookeeper_tpu.serving.service import ServingConfig

__all__ = [
    "BrownOut",
    "CheckpointWatcher",
    "CircuitBreaker",
    "DeadlineExpiredError",
    "DecodeEngine",
    "DecodeMetrics",
    "DecodeScheduler",
    "DecodeStream",
    "DisaggPartitioner",
    "DisaggScheduler",
    "DisaggServingConfig",
    "FleetMetrics",
    "FleetResponse",
    "FleetRouter",
    "FleetUnavailableError",
    "InferenceEngine",
    "PageTransfer",
    "PageTransferError",
    "LMServingConfig",
    "MicroBatcher",
    "OverloadGuard",
    "PendingResult",
    "PredictedMissError",
    "RejectedError",
    "ReplicaHandle",
    "ServingConfig",
    "ServingMetrics",
    "SpeculativeDecoding",
    "WorkerCrashedError",
]
