"""The serving config tree: one component from checkpoint to hot engine.

``ServingConfig`` is the ``Experiment``-shaped citizen of the config
system (same ``key=value`` CLI, same scoped-field wiring) for the
inference half of the north star: point it at a deployment artifact —
a ``save_model`` export or a full ``Checkpointer`` directory — pick EMA
vs raw weights, and ``build_service()`` returns a warmed engine +
batcher pair ready for traffic.

``run()`` is the demo/bench driver (a real deployment would wrap
``build_service()`` in its transport of choice): it feeds a
deterministic stream of variable-size synthetic requests through the
batcher, then prints ONE JSON line of serving metrics (latency
percentiles, bucket fill, padding waste, qps) through the same
``MetricsWriter`` sinks training uses — so
``python examples/serve_classifier.py ServeDigits checkpoint=...`` is an
end-to-end smoke of the whole subsystem.
"""

import json
import time
from typing import Any, Dict, Optional

from zookeeper_tpu.core import ComponentField, Field, component, pretty_print
from zookeeper_tpu.models.base import Model
from zookeeper_tpu.parallel.partitioner import (
    Partitioner,
    SingleDevicePartitioner,
)
from zookeeper_tpu.serving.batcher import MicroBatcher
from zookeeper_tpu.serving.engine import InferenceEngine
from zookeeper_tpu.serving.metrics import ServingMetrics
from zookeeper_tpu.training.experiment import Experiment
from zookeeper_tpu.training.metrics import CompositeMetricsWriter, MetricsWriter


def run_teardown_steps(steps, *, suppress: bool = False) -> None:
    """The service-teardown contract, shared by ``ServingConfig`` and
    ``LMServingConfig``: every step runs even when an earlier one
    raises (a failed watcher stop must not leak the /metrics port or
    the worker thread), and the FIRST failure is re-raised at the end
    unless ``suppress`` (error paths, where a cleanup failure must not
    mask the original exception)."""
    first: Optional[BaseException] = None
    for step in steps:
        try:
            step()
        except BaseException as e:
            if first is None:
                first = e
    if first is not None and not suppress:
        raise first


@component
class ServingConfig(Experiment):
    """Configurable inference service over an exported model.

    Subclass with ``@task`` (like the training examples do for
    ``TrainingExperiment``) to get a ``serve``-style CLI entry point —
    see ``examples/serve_classifier.py``.
    """

    model: Model = ComponentField()
    partitioner: Partitioner = ComponentField(SingleDevicePartitioner)
    engine: InferenceEngine = ComponentField(InferenceEngine)
    batcher: MicroBatcher = ComponentField(MicroBatcher)
    metrics: ServingMetrics = ComponentField(ServingMetrics)
    #: Same pluggable sink family as training (``writer.jsonl.path=...``
    #: / ``writer.tensorboard.log_dir=...``).
    writer: MetricsWriter = ComponentField(CompositeMetricsWriter)

    #: Deployment artifact: a ``save_model`` export or a full
    #: ``Checkpointer`` directory (latest step). None = fresh-initialized
    #: weights (compile/latency smoke without a training run).
    checkpoint: Optional[str] = Field(None)
    #: EMA-vs-raw weight selection (``select_inference_weights``):
    #: "auto" ships the EMA shadow when the checkpoint carries one —
    #: the same artifact ``ema_decay`` + ``export_model_to`` produce.
    weights: str = Field("auto")

    #: Per-example input geometry (images; token models drive the engine
    #: programmatically with ``seq_buckets``).
    height: int = Field(224)
    width: int = Field(224)
    channels: int = Field(3)
    num_classes: int = Field(1000)
    seed: int = Field(0)

    #: Pre-compile every bucket before serving (warm path: first request
    #: never pays XLA).
    warmup: bool = Field(True)
    #: Serve a LIVE training run: when ``checkpoint`` is a Checkpointer
    #: directory, a background watcher polls it for newly finalized
    #: steps and hot-swaps each one into the warmed engine — no
    #: recompiles, no restart (``InferenceEngine.watch_checkpoints``;
    #: docs/DESIGN.md §12). The ``weights`` Field picks EMA vs raw for
    #: the swaps exactly as it does for the initial load.
    watch: bool = Field(False)
    #: Watcher poll interval, seconds.
    watch_poll_s: float = Field(2.0)
    #: Demo-driver knobs for ``run()``: how many synthetic requests, and
    #: the largest request size in the stream.
    requests: int = Field(64)
    max_request: int = Field(8)
    verbose: bool = Field(True)
    #: Live observability endpoint (docs/DESIGN.md §13): port for a
    #: stdlib HTTP server exposing every ``ServingMetrics`` series at
    #: ``/metrics`` (Prometheus text exposition) plus ``/statusz``
    #: (engine compile counts, batcher queue, live-weights step) and
    #: ``/trace``. -1 = off (default); 0 = ephemeral port (readable via
    #: ``self.obs_server.port`` — the CI scrape smoke uses this).
    metrics_port: int = Field(-1)
    #: Flight recorder (docs/DESIGN.md §16): when set, a
    #: ``FlightRecorder`` writing rate-limited debug bundles to this
    #: directory is installed for the service's lifetime — worker
    #: crashes, recompiles, watchdog anomalies, fault injections and
    #: ``POST /debugz`` each dump the trace ring, /metrics text,
    #: program ledger, statusz sections and the RequestLog tail into
    #: one directory. None = off.
    flight_recorder_dir: Optional[str] = Field(None)
    #: Minimum seconds between flight-recorder bundles (rate limit; a
    #: crash loop must not fill the disk). Manual ``/debugz`` triggers
    #: bypass it.
    flight_recorder_interval_s: float = Field(30.0)

    @property
    def input_shape(self):
        return (self.height, self.width, self.channels)

    def build_service(self):
        """Load weights, bind + warm the engine, bind the batcher.
        Returns ``(engine, batcher)`` (also kept on self)."""
        if self.weights not in ("auto", "ema", "raw"):
            # Pure config: fail before any checkpoint IO / compile.
            raise ValueError(
                f"weights={self.weights!r} unknown; choose auto/ema/raw."
            )
        if self.requests < 0 or self.max_request < 1:
            raise ValueError(
                f"requests={self.requests} must be >= 0 and "
                f"max_request={self.max_request} >= 1."
            )
        module = self.model.build(self.input_shape, self.num_classes)
        # Watcher baseline, captured BEFORE the load: the load below
        # binds at-least-this step, so any step finalizing during
        # load/warmup stays NEWER than the baseline and the first poll
        # swaps it in (listing after warmup could mark a step "live"
        # that was never actually bound).
        watch_baseline = None
        if self.watch and self.checkpoint:
            from zookeeper_tpu.training.checkpoint import finalized_steps

            steps = finalized_steps(self.checkpoint)
            watch_baseline = steps[-1] if steps else None
        if self.checkpoint:
            import jax

            from zookeeper_tpu.training.checkpoint import load_inference_model

            abstract = jax.eval_shape(
                lambda: self.model.initialize(
                    module, self.input_shape, seed=self.seed
                )
            )
            params, model_state = load_inference_model(
                self.checkpoint,
                weights=self.weights,
                params_like=abstract[0],
                model_state_like=abstract[1],
            )
        else:
            params, model_state = self.model.initialize(
                module, self.input_shape, seed=self.seed
            )
        self.partitioner.setup()
        self.engine.bind(
            module.apply,
            params,
            model_state,
            self.input_shape,
            dtype=self.model.dtype(),
            partitioner=self.partitioner,
        )
        if self.warmup:
            self.engine.warmup()
        self.batcher.bind(self.engine, metrics=self.metrics)
        if self.watch:
            if not self.checkpoint:
                raise ValueError(
                    "watch=True needs checkpoint= pointing at a live "
                    "Checkpointer directory to stream steps from."
                )
            # The pre-load baseline seeds the watcher so startup does
            # not redundantly reload the step the load above already
            # bound; a step that finalized since is newer than the
            # baseline and the eager first poll swaps it in.
            object.__setattr__(
                self,
                "watcher",
                self.engine.watch_checkpoints(
                    self.checkpoint,
                    weights=self.weights,
                    poll_interval_s=self.watch_poll_s,
                    metrics=self.metrics,
                    initial_step=watch_baseline,
                ),
            )
        if self.metrics_port >= 0 or self.flight_recorder_dir:
            try:
                if self.flight_recorder_dir:
                    self._start_flight_recorder()
                if self.metrics_port >= 0:
                    self._start_obs_server()
            except BaseException:
                # The service half-exists (watcher daemon polling,
                # batcher bound) and run()'s cleanup paths only cover
                # what build_service RETURNED — a bind failure (busy
                # port) must not leak live threads into a caller that
                # catches the error.
                self._teardown_service(suppress=True)
                raise
        return self.engine, self.batcher

    def _request_log_status(self):
        """``/statusz`` + bundle section: the recent terminal-request
        tail (rid, timestamps, outcome — docs/DESIGN.md §16)."""
        log = self.batcher.request_log
        return log.as_status() if log is not None else {}

    def _start_flight_recorder(self):
        from zookeeper_tpu.observability import recorder as _recorder
        from zookeeper_tpu.observability.registry import default_registry

        rec = _recorder.arm(
            self.flight_recorder_dir,
            registries=[default_registry(), self.metrics.registry],
            status_providers={
                "serving": self._obs_status,
                "requests": self._request_log_status,
            },
            request_logs={"serving": self.batcher.request_log},
            min_interval_s=self.flight_recorder_interval_s,
        )
        object.__setattr__(self, "flight_recorder", rec)
        if self.verbose:
            print(
                f"flight recorder armed: {self.flight_recorder_dir} "
                f"(>= {self.flight_recorder_interval_s:.0f}s between "
                "bundles; POST /debugz for a manual one)",
                flush=True,
            )
        return rec

    def _stop_flight_recorder(self):
        from zookeeper_tpu.observability import recorder as _recorder

        rec = getattr(self, "flight_recorder", None)
        if rec is not None:
            object.__setattr__(self, "flight_recorder", None)
            _recorder.disarm(rec)

    def _obs_status(self):
        """``/statusz`` section: the serving-process vitals an operator
        (or health probe) checks before trusting the metrics."""
        watcher = getattr(self, "watcher", None)
        return {
            "model": type(self.model).__name__,
            "weights": self.weights,
            "batch_buckets": [int(b) for b in self.engine.batch_buckets],
            "compiles": self.engine.compile_count,
            # Post-warmup request-path compiles: nonzero means traffic
            # is stalling on XLA (the recompile watchdog fired).
            "recompiles_detected": self.engine.recompiles_detected,
            "queue_rows": self.batcher.queue_rows,
            # §21: packed (bit-packed binary) deployments additionally
            # publish zk_serve_mfu_int8 against the int8 roofline.
            "packed_deployment": self.engine.packed_deployment,
            "watcher_alive": (
                watcher.alive if watcher is not None else None
            ),
            "serving_weights_step": self.metrics.totals[
                "serving_weights_step"
            ],
        }

    def _start_obs_server(self):
        from zookeeper_tpu.observability import (
            DeviceProbe,
            ObservabilityServer,
        )
        from zookeeper_tpu.observability.registry import default_registry

        server = ObservabilityServer(
            [default_registry(), self.metrics.registry],
            port=self.metrics_port,
            status_providers={
                "serving": self._obs_status,
                "requests": self._request_log_status,
            },
        )
        server.start()
        object.__setattr__(self, "obs_server", server)
        # Live HBM gauges for the serving process (zk-device-probe):
        # eager first poll so zk_hbm_* renders from the first scrape.
        probe = DeviceProbe()
        probe.poll_once()
        probe.start()
        object.__setattr__(self, "obs_probe", probe)
        if self.verbose:
            print(
                f"observability endpoint: {server.url}/metrics",
                flush=True,
            )
        return server

    def finish_report(
        self,
        *,
        warm_compiles: int,
        n_requests: int,
        dt: float,
        writer_extra: Optional[Dict[str, float]] = None,
        result_extra: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """The ONE reporting path (shared with serve-task subclasses so
        the JSON contract — compiles/recompiles_after_warmup/qps/serve
        metric keys — can never fork): emit the metrics snapshot through
        the writer, assemble + print the result line, close the
        batcher."""
        qps = n_requests / dt if dt > 0 else 0.0
        snapshot = self.metrics.emit(
            self.writer, step=0, extra={"qps": qps, **(writer_extra or {})}
        )
        self.writer.flush()
        result = {
            **{k: round(float(v), 4) for k, v in snapshot.items()},
            "model": type(self.model).__name__,
            "weights": self.weights,
            "batch_buckets": [int(b) for b in self.engine.batch_buckets],
            "compiles": self.engine.compile_count,
            "recompiles_after_warmup": (
                self.engine.compile_count - warm_compiles
            ),
            "requests": n_requests,
            "qps": round(qps, 1),
            **(result_extra or {}),
        }
        if self.verbose:
            print(json.dumps(result), flush=True)
        self._teardown_service()
        return result

    def _teardown_obs_server(self):
        """Idempotent endpoint teardown — the server holds an OS port
        (unlike the daemon threads), so EVERY exit path must release it
        or a same-port rebuild in this process dies with EADDRINUSE."""
        server = getattr(self, "obs_server", None)
        if server is not None:
            object.__setattr__(self, "obs_server", None)
            server.stop()
        probe = getattr(self, "obs_probe", None)
        if probe is not None:
            object.__setattr__(self, "obs_probe", None)
            probe.stop()

    def _teardown_service(self, *, suppress: bool = False) -> None:
        """The ONE teardown sequence (watcher daemon, /metrics port,
        flight recorder, batcher worker) shared by every exit path."""
        watcher = getattr(self, "watcher", None)
        steps = [
            self._teardown_obs_server,
            self._stop_flight_recorder,
            self.batcher.close,
        ]
        if watcher is not None:
            steps.insert(0, watcher.stop)
        run_teardown_steps(steps, suppress=suppress)

    def run(self) -> Dict[str, Any]:
        """Serve a deterministic synthetic request stream and report."""
        import numpy as np

        if self.verbose:
            print(pretty_print(self), flush=True)
        engine, batcher = self.build_service()
        try:
            warm_compiles = engine.compile_count
            rng = np.random.default_rng(self.seed)
            t0 = time.perf_counter()
            pending = []
            rows = 0
            for _ in range(self.requests):
                n = int(rng.integers(1, self.max_request + 1))
                x = rng.normal(size=(n, *self.input_shape)).astype(
                    self.model.dtype()
                )
                pending.append((n, batcher.submit(x)))
                rows += n
            batcher.flush()
            dt = time.perf_counter() - t0
            for n, handle in pending:
                out = handle.result()
                assert out.shape[0] == n, (out.shape, n)
        except BaseException:
            # finish_report (the happy-path teardown) won't run: release
            # the endpoint's port and the watcher/worker threads here.
            self._teardown_service(suppress=True)
            raise
        return self.finish_report(
            warm_compiles=warm_compiles,
            n_requests=self.requests,
            dt=dt,
            writer_extra={"rows_per_sec": (rows / dt) if dt > 0 else 0.0},
        )
