"""Serving observability: latency percentiles, queue depth, padding cost.

The serving analogue of the training metrics writers: the batcher and
service record per-request and per-dispatch samples here (host-side
floats only — recording never adds device syncs), and ``emit()`` flushes
an aggregated snapshot through the existing
:class:`~zookeeper_tpu.training.metrics.MetricsWriter` family, so one
sink config observes both halves of the system.

The tracked quantities are the levers of the serving cost model
(docs/DESIGN.md §8):

- ``latency_p50/p95/p99_ms`` — per-request submit-to-result wall time;
  the tail is what ``max_delay_ms`` trades against throughput.
- ``queue_depth`` — pending rows at submit time; sustained growth means
  the engine is saturated (widen buckets or add chips).
- ``bucket_fill`` — real rows / bucket rows per dispatch; low fill says
  the delay window closes before traffic accumulates.
- ``padding_waste`` — padded rows / bucket rows; the compute thrown
  away to shape quantization (more buckets shrink it).
"""

from collections import deque
from typing import Dict, Mapping, Optional

import numpy as np

from zookeeper_tpu.core import Field, component


@component
class ServingMetrics:
    """Bounded-window aggregator for serving samples.

    All recorders are O(1) appends into fixed-size deques (a serving
    process runs indefinitely; unbounded sample lists would be a slow
    leak). ``snapshot()`` reduces the current window; counters
    (``requests``/``rows``/``dispatches``) are lifetime totals.
    """

    #: Samples retained per series (latency/fill/depth). Percentiles are
    #: computed over this sliding window.
    window: int = Field(4096)

    def _series(self, name: str) -> deque:
        store = getattr(self, "_store", None)
        if store is None:
            store = {}
            object.__setattr__(self, "_store", store)
            object.__setattr__(
                self,
                "_totals",
                {
                    "requests": 0,
                    "rows": 0,
                    "dispatches": 0,
                    # Resilience counters (docs/DESIGN.md §10): shed
                    # submits, deadline-failed requests, and worker
                    # crash/restart cycles. Lifetime totals like the
                    # rest; the shed RATE is rejected/(rejected+requests).
                    "rejected": 0,
                    "deadline_expired": 0,
                    "worker_restarts": 0,
                    # Checkpoint→serving streaming (docs/DESIGN.md §12):
                    # hot-swap count and WHICH training step is live —
                    # the dashboard gauge that says how stale the served
                    # model is relative to the training run (-1 = the
                    # bind()-time weights, never swapped).
                    "weight_swaps": 0,
                    "serving_weights_step": -1,
                    # Nonzero = the watcher daemon died on a fatal
                    # error and serving_weights_step is FROZEN, not
                    # live-following (alert on this, not on staleness).
                    "watcher_stopped": 0,
                },
            )
        if name not in store:
            store[name] = deque(maxlen=max(1, int(self.window)))
        return store[name]

    # -- recorders (called by MicroBatcher / ServingConfig) --------------

    def record_request(self, latency_ms: float, rows: int) -> None:
        self._series("latency_ms").append(float(latency_ms))
        self._totals["requests"] += 1
        self._totals["rows"] += int(rows)

    def record_queue_depth(self, rows: int) -> None:
        self._series("queue_depth").append(float(rows))

    def record_rejected(self) -> None:
        """A submit was shed (``RejectedError``) instead of enqueued."""
        self._series("latency_ms")  # ensure initialized
        self._totals["rejected"] += 1

    def record_deadline_expired(self) -> None:
        """A request's deadline elapsed before it was served."""
        self._series("latency_ms")
        self._totals["deadline_expired"] += 1

    def record_worker_restart(self) -> None:
        """The async batcher worker died and was scheduled for restart
        (its queued/in-flight requests were failed cleanly)."""
        self._series("latency_ms")
        self._totals["worker_restarts"] += 1

    def record_weight_swap(self, swap_ms: float, step: int) -> None:
        """A checkpoint hot-swap landed: ``step``'s weights are now
        live (``CheckpointWatcher``/``swap_weights``); ``swap_ms`` is
        load+place+swap wall time."""
        self._series("weight_swap_ms").append(float(swap_ms))
        self._totals["weight_swaps"] += 1
        self._totals["serving_weights_step"] = int(step)

    def record_watcher_stopped(self) -> None:
        """The checkpoint watcher's daemon died on a fatal error:
        ``serving_weights_step`` is frozen from here on."""
        self._series("latency_ms")
        self._totals["watcher_stopped"] += 1

    def record_weights_step(self, step: int) -> None:
        """Set the live-weights gauge WITHOUT counting a swap — the
        bind-time weights of a service that loaded ``step`` at startup
        (``CheckpointWatcher(initial_step=...)``)."""
        self._series("latency_ms")
        self._totals["serving_weights_step"] = int(step)

    def record_dispatch(self, real_rows: int, bucket_rows: int) -> None:
        if bucket_rows <= 0:
            return
        self._series("bucket_fill").append(real_rows / bucket_rows)
        self._series("padding_waste").append(
            (bucket_rows - real_rows) / bucket_rows
        )
        self._totals["dispatches"] += 1

    # -- reduction -------------------------------------------------------

    @property
    def totals(self) -> Dict[str, int]:
        self._series("latency_ms")  # ensure initialized
        return dict(self._totals)

    def snapshot(self) -> Dict[str, float]:
        """Aggregate the current window into a flat ``{name: float}``
        mapping (absent series are simply omitted, so an idle service
        emits only its counters)."""
        self._series("latency_ms")
        out: Dict[str, float] = {
            k: float(v) for k, v in self._totals.items()
        }
        lat = self._store.get("latency_ms")
        if lat:
            arr = np.asarray(lat)
            out["latency_p50_ms"] = float(np.percentile(arr, 50))
            out["latency_p95_ms"] = float(np.percentile(arr, 95))
            out["latency_p99_ms"] = float(np.percentile(arr, 99))
            out["latency_mean_ms"] = float(arr.mean())
        for name in (
            "queue_depth", "bucket_fill", "padding_waste", "weight_swap_ms",
        ):
            series = self._store.get(name)
            if series:
                out[f"{name}_mean"] = float(np.mean(series))
        return out

    def emit(
        self, writer, step: int = 0, extra: Optional[Mapping[str, float]] = None
    ) -> Dict[str, float]:
        """Write the snapshot through a training-family MetricsWriter
        under the ``serve/`` prefix; returns the snapshot."""
        snap = self.snapshot()
        scalars = {f"serve/{k}": float(v) for k, v in snap.items()}
        if extra:
            scalars.update(
                {f"serve/{k}": float(v) for k, v in extra.items()}
            )
        writer.write_scalars(int(step), scalars)
        return snap

    def reset(self) -> None:
        object.__setattr__(self, "_store", None)
