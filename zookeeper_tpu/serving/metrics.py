"""Serving observability: latency percentiles, queue depth, padding cost.

The serving analogue of the training metrics writers: the batcher and
service record per-request and per-dispatch samples here (host-side
floats only — recording never adds device syncs), and ``emit()`` flushes
an aggregated snapshot through the existing
:class:`~zookeeper_tpu.training.metrics.MetricsWriter` family, so one
sink config observes both halves of the system.

Since the observability layer landed (docs/DESIGN.md §13), the
aggregator is implemented ON TOP of the typed registry
(``observability.registry``): every lifetime total is a
:class:`~zookeeper_tpu.observability.registry.Counter` (or Gauge for
``serving_weights_step``), every sampled series additionally feeds a
fixed-bucket Histogram, and the whole instrument set renders live at
``/metrics`` in Prometheus text via ``ServingConfig.metrics_port``.
The PUBLIC API is bit-compatible with the pre-registry class: the
``record_*`` recorders, ``totals``, ``snapshot()`` (exact
``np.percentile`` over the bounded sample window — histograms are for
scraping, not for the snapshot numbers), ``emit()`` and ``reset()``
behave identically; recording is additionally thread-safe (registry
instruments are locked, window appends are GIL-atomic deque ops) since
the async batcher worker, watcher daemon, and submitter threads all
record concurrently.

The tracked quantities are the levers of the serving cost model
(docs/DESIGN.md §8):

- ``latency_p50/p95/p99_ms`` — per-request submit-to-result wall time;
  the tail is what ``max_delay_ms`` trades against throughput.
- ``queue_depth`` — pending rows at submit time; sustained growth means
  the engine is saturated (widen buckets or add chips).
- ``bucket_fill`` — real rows / bucket rows per dispatch; low fill says
  the delay window closes before traffic accumulates.
- ``padding_waste`` — padded rows / bucket rows; the compute thrown
  away to shape quantization (more buckets shrink it).
"""

import threading
from collections import deque
from typing import Dict, Mapping, Optional

import numpy as np

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.observability.registry import (
    DEFAULT_MS_BUCKETS,
    DEFAULT_RATIO_BUCKETS,
    MetricsRegistry,
)

#: Exposition name prefix: every instrument this component registers
#: renders as ``zk_serving_<name>`` at ``/metrics``.
_PREFIX = "zk_serving_"

#: Guards first-touch creation of an instance's instrument set: two
#: threads racing the first record_* must share ONE registry (a dropped
#: half-initialized one would silently eat its thread's samples).
_INIT_LOCK = threading.Lock()


# -- shared windowed-registry machinery -----------------------------------
#
# ServingMetrics and DecodeMetrics are the same aggregator shape with
# different instrument tables: lazily-built registry state ("counters"/
# "gauges"/"hist" dicts + bounded sample "windows"), O(1) thread-safe
# recorders, exact-percentile snapshots, in-place reset. The shape lives
# HERE once so a fix to the shared contract (the racing-first-touch
# init, the reset-zeros-in-place /metrics guarantee) lands in one place.


def _get_or_build_obs(metrics, build) -> dict:
    """Double-checked lazy init of a metrics component's ``_obs_state``
    (one registry per instance even under racing first recorders)."""
    obs = getattr(metrics, "_obs_state", None)
    if obs is None:
        with _INIT_LOCK:
            obs = getattr(metrics, "_obs_state", None)
            if obs is not None:
                return obs
            obs = build()
            object.__setattr__(metrics, "_obs_state", obs)
    return obs


def _window_series(obs: dict, name: str, window: int) -> deque:
    series = obs["windows"].get(name)
    if series is None:
        # setdefault: two threads racing the first sample of a series
        # must share ONE deque, not drop one of them.
        series = obs["windows"].setdefault(
            name, deque(maxlen=max(1, int(window)))
        )
    return series


def _observe_sample(obs: dict, name: str, value: float, window: int) -> None:
    """One sample: window append (exact percentile source) + fixed-
    bucket histogram observe (live scrape source)."""
    _window_series(obs, name, window).append(float(value))
    hist = obs["hist"].get(name)
    if hist is not None:
        hist.observe(value)


def _reset_obs(metrics) -> None:
    """Zero every instrument IN PLACE. The registry and instrument
    objects survive (an ``ObservabilityServer`` that captured
    ``registry`` at startup keeps rendering this aggregator — a scraper
    just sees an ordinary counter reset); dropping ``_obs_state``
    instead would silently disconnect ``/metrics`` from all future
    samples."""
    obs = getattr(metrics, "_obs_state", None)
    if obs is None:
        return
    for inst in (
        *obs["counters"].values(),
        *obs["gauges"].values(),
        *obs["hist"].values(),
    ):
        inst.reset()
    obs["windows"].clear()


def _emit_snapshot(metrics, writer, step, extra, prefix) -> Dict[str, float]:
    """Write ``metrics.snapshot()`` through a training-family
    MetricsWriter under ``prefix/``; returns the snapshot."""
    snap = metrics.snapshot()
    scalars = {f"{prefix}/{k}": float(v) for k, v in snap.items()}
    if extra:
        scalars.update(
            {f"{prefix}/{k}": float(v) for k, v in extra.items()}
        )
    writer.write_scalars(int(step), scalars)
    return snap

#: Lifetime counters, in the order ``totals`` has always reported them.
_COUNTER_NAMES = (
    "requests",
    "rows",
    "dispatches",
    # Resilience counters (docs/DESIGN.md §10): shed submits,
    # deadline-failed requests, and worker crash/restart cycles. The
    # shed RATE is rejected/(rejected+requests).
    "rejected",
    "deadline_expired",
    "worker_restarts",
    # Checkpoint→serving streaming (docs/DESIGN.md §12).
    "weight_swaps",
    # Nonzero = the watcher daemon died on a fatal error and
    # serving_weights_step is FROZEN, not live-following (alert on
    # this, not on staleness).
    "watcher_stopped",
)


@component
class ServingMetrics:
    """Bounded-window aggregator for serving samples.

    All recorders are O(1): a locked counter bump and/or an append into
    a fixed-size deque plus a histogram observe (a serving process runs
    indefinitely; unbounded sample lists would be a slow leak).
    ``snapshot()`` reduces the current window; counters
    (``requests``/``rows``/``dispatches``/...) are lifetime totals.
    """

    #: Samples retained per series (latency/fill/depth). Percentiles are
    #: computed over this sliding window.
    window: int = Field(4096)

    # -- lazy state ------------------------------------------------------

    def _obs(self) -> dict:
        return _get_or_build_obs(self, self._build_obs)

    def _build_obs(self) -> dict:
        registry = MetricsRegistry()
        return {
            "registry": registry,
            "counters": {
                name: registry.counter(
                    _PREFIX + name, help=f"lifetime {name} total"
                )
                for name in _COUNTER_NAMES
            },
            "gauges": {
                # WHICH training step is live — the dashboard gauge
                # that says how stale the served model is relative to
                # the training run (-1 = the bind()-time weights,
                # never swapped).
                "weights_step": registry.gauge(
                    _PREFIX + "serving_weights_step",
                    help="training step whose weights are live (-1 = "
                    "bind-time weights)",
                    initial=-1,
                ),
                "queue_depth": registry.gauge(
                    _PREFIX + "queue_depth",
                    help="pending rows at the last submit",
                ),
            },
            "hist": {
                "latency_ms": registry.histogram(
                    _PREFIX + "latency_ms",
                    buckets=DEFAULT_MS_BUCKETS,
                    help="per-request submit-to-result wall time",
                ),
                "bucket_fill": registry.histogram(
                    _PREFIX + "bucket_fill",
                    buckets=DEFAULT_RATIO_BUCKETS,
                    help="real rows / bucket rows per dispatch",
                ),
                "padding_waste": registry.histogram(
                    _PREFIX + "padding_waste",
                    buckets=DEFAULT_RATIO_BUCKETS,
                    help="padded rows / bucket rows per dispatch",
                ),
                "weight_swap_ms": registry.histogram(
                    _PREFIX + "weight_swap_ms",
                    buckets=DEFAULT_MS_BUCKETS,
                    help="checkpoint hot-swap load+place+swap time",
                ),
            },
            "windows": {},
        }

    @property
    def registry(self) -> MetricsRegistry:
        """The typed instrument registry backing this aggregator —
        attach it to an ``ObservabilityServer`` to scrape every series
        live (``ServingConfig.metrics_port`` does)."""
        return self._obs()["registry"]

    def _series(self, name: str) -> deque:
        return _window_series(self._obs(), name, self.window)

    def _observe(self, name: str, value: float) -> None:
        _observe_sample(self._obs(), name, value, self.window)

    # -- recorders (called by MicroBatcher / ServingConfig) --------------

    def record_request(self, latency_ms: float, rows: int) -> None:
        obs = self._obs()
        self._observe("latency_ms", latency_ms)
        obs["counters"]["requests"].inc()
        obs["counters"]["rows"].inc(int(rows))

    def record_queue_depth(self, rows: int) -> None:
        self._series("queue_depth").append(float(rows))
        self._obs()["gauges"]["queue_depth"].set(rows)

    def record_rejected(self) -> None:
        """A submit was shed (``RejectedError``) instead of enqueued."""
        self._obs()["counters"]["rejected"].inc()

    def record_deadline_expired(self) -> None:
        """A request's deadline elapsed before it was served."""
        self._obs()["counters"]["deadline_expired"].inc()

    def record_worker_restart(self) -> None:
        """The async batcher worker died and was scheduled for restart
        (its queued/in-flight requests were failed cleanly)."""
        self._obs()["counters"]["worker_restarts"].inc()

    def record_weight_swap(self, swap_ms: float, step: int) -> None:
        """A checkpoint hot-swap landed: ``step``'s weights are now
        live (``CheckpointWatcher``/``swap_weights``); ``swap_ms`` is
        load+place+swap wall time."""
        obs = self._obs()
        self._observe("weight_swap_ms", swap_ms)
        obs["counters"]["weight_swaps"].inc()
        obs["gauges"]["weights_step"].set(int(step))

    def record_watcher_stopped(self) -> None:
        """The checkpoint watcher's daemon died on a fatal error:
        ``serving_weights_step`` is frozen from here on."""
        self._obs()["counters"]["watcher_stopped"].inc()

    def record_weights_step(self, step: int) -> None:
        """Set the live-weights gauge WITHOUT counting a swap — the
        bind-time weights of a service that loaded ``step`` at startup
        (``CheckpointWatcher(initial_step=...)``)."""
        self._obs()["gauges"]["weights_step"].set(int(step))

    def record_dispatch(self, real_rows: int, bucket_rows: int) -> None:
        if bucket_rows <= 0:
            return
        self._observe("bucket_fill", real_rows / bucket_rows)
        self._observe(
            "padding_waste", (bucket_rows - real_rows) / bucket_rows
        )
        self._obs()["counters"]["dispatches"].inc()

    # -- reduction -------------------------------------------------------

    @property
    def weights_step(self) -> int:
        """The live-weights gauge as a plain int (-1 = bind-time
        weights) — the cheap read the per-request RequestLog summaries
        stamp without assembling ``totals``."""
        return int(self._obs()["gauges"]["weights_step"].value)

    @property
    def totals(self) -> Dict[str, int]:
        obs = self._obs()
        out: Dict[str, int] = {}
        for name in _COUNTER_NAMES:
            out[name] = int(obs["counters"][name].value)
            if name == "weight_swaps":
                # Historical key order: the gauge sits between the swap
                # counter and watcher_stopped.
                out["serving_weights_step"] = int(
                    obs["gauges"]["weights_step"].value
                )
        return out

    def snapshot(self) -> Dict[str, float]:
        """Aggregate the current window into a flat ``{name: float}``
        mapping (absent series are simply omitted, so an idle service
        emits only its counters)."""
        windows = self._obs()["windows"]
        out: Dict[str, float] = {
            k: float(v) for k, v in self.totals.items()
        }
        lat = windows.get("latency_ms")
        if lat:
            arr = np.asarray(lat)
            out["latency_p50_ms"] = float(np.percentile(arr, 50))
            out["latency_p95_ms"] = float(np.percentile(arr, 95))
            out["latency_p99_ms"] = float(np.percentile(arr, 99))
            out["latency_mean_ms"] = float(arr.mean())
        for name in (
            "queue_depth", "bucket_fill", "padding_waste", "weight_swap_ms",
        ):
            series = windows.get(name)
            if series:
                out[f"{name}_mean"] = float(np.mean(series))
        return out

    def emit(
        self, writer, step: int = 0, extra: Optional[Mapping[str, float]] = None
    ) -> Dict[str, float]:
        """Write the snapshot through a training-family MetricsWriter
        under the ``serve/`` prefix; returns the snapshot."""
        return _emit_snapshot(self, writer, step, extra, "serve")

    def reset(self) -> None:
        """Zero every series IN PLACE (see :func:`_reset_obs` for the
        live-``/metrics`` contract)."""
        _reset_obs(self)
