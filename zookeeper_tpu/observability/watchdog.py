"""Step-time anomaly watchdog: EWMA baseline + MAD spread over a
rolling window.

The fused training loop and the serving dispatcher both produce a
steady stream of durations (seconds per step/slab, seconds per
coalesced dispatch). A straggler slab (a preempting neighbor, a
background compaction, an ICI link flap) or a post-warmup recompile
silently eats throughput today — visible only as a slightly worse
epoch rate hours later. The watchdog makes those self-announcing:

- **Baseline**: an exponentially-weighted moving average (EWMA,
  ``alpha`` default 0.2 — the baseline absorbs a genuine level shift
  in ~1/alpha observations).
- **Spread**: 1.4826 x the median absolute deviation (MAD) of the
  rolling window — the robust sigma estimate, immune to the very
  outliers being hunted (a stddev would inflate itself on each
  anomaly and go blind). Recomputed every ``recompute_every``
  observations, not every sample: ``observe`` is on the step path and
  its cost rides the same <= 2% bench budget as the trace spans
  (``ZK_BENCH_OBS=1`` measures it).
- **Trigger**: after ``warmup`` observations, a duration is anomalous
  when it exceeds ``ewma + threshold * mad_sigma`` AND
  ``min_ratio * ewma`` — the ratio guard is the false-positive floor
  for near-perfectly-steady cadences, where MAD collapses toward 0
  and any microsecond of jitter would otherwise fire
  (docs/DESIGN.md §14 records the policy).

On trigger: a ``step_time_anomaly`` trace event (step attribution +
observed/baseline ms) and a ``zk_step_time_anomalies_total{stream=}``
counter bump. Anomalous samples still update the EWMA and the window —
a persistent regression fires for a bounded burst (~1/alpha
observations) while it is news, then becomes the new baseline instead
of alerting forever.

Anomalies are also a FLIGHT-RECORDER trigger source (docs/DESIGN.md
§16): every trigger notifies the process-global recorder (one global
read when none is installed) so the evidence — trace ring, metrics,
RequestLog — is bundled while the straggler's spans still exist; the
``on_anomaly`` callback seam lets a caller subscribe its own handler
on top (called OUTSIDE the watchdog lock; its failures are logged,
never raised into the step path).
"""

import logging
import threading
from collections import deque
from typing import Callable, Optional

from zookeeper_tpu.observability import recorder as _recorder
from zookeeper_tpu.observability import trace as _trace

logger = logging.getLogger(__name__)
from zookeeper_tpu.observability.registry import (
    MetricsRegistry,
    default_registry,
)

__all__ = ["StepTimeWatchdog"]

#: MAD -> sigma for normally-distributed noise.
_MAD_SIGMA = 1.4826


class StepTimeWatchdog:
    """Anomaly detector over one duration stream.

    ``observe(seconds, step=)`` returns True when the sample is
    anomalous. Thread-safe (the serving dispatcher's worker thread and
    a test's assertions may race); the lock covers a deque append and
    a few float ops, with the MAD re-sort amortized over
    ``recompute_every`` samples.
    """

    def __init__(
        self,
        stream: str,
        *,
        window: int = 64,
        warmup: int = 5,
        alpha: float = 0.2,
        threshold: float = 6.0,
        min_ratio: float = 1.5,
        min_excess_s: float = 0.0,
        recompute_every: int = 8,
        registry: Optional[MetricsRegistry] = None,
        on_anomaly: Optional[
            Callable[[str, float, Optional[int]], None]
        ] = None,
    ) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha={alpha} must be in (0, 1].")
        if window < 4:
            raise ValueError(f"window={window} must be >= 4.")
        if warmup < 1:
            raise ValueError(f"warmup={warmup} must be >= 1.")
        if min_ratio < 1.0:
            raise ValueError(
                f"min_ratio={min_ratio} must be >= 1 (an 'anomaly' "
                "faster than baseline is just a good step)."
            )
        self.stream = str(stream)
        self.warmup = int(warmup)
        self.alpha = float(alpha)
        self.threshold = float(threshold)
        self.min_ratio = float(min_ratio)
        #: Absolute excess-over-baseline floor: sub-floor spikes are
        #: never anomalies, however many MADs they span. The guard for
        #: microsecond-cadence streams (a CPU test loop, an idle
        #: dispatcher) where baseline AND spread are so tiny that
        #: host-scheduler jitter satisfies every relative test.
        self.min_excess_s = float(min_excess_s)
        #: Subscriber seam: ``on_anomaly(stream, seconds, step)`` fires
        #: per flagged sample, after the counter/event, outside the
        #: lock. The flight recorder is notified regardless (module
        #: global; no-op when none installed).
        self.on_anomaly = on_anomaly
        self._recompute_every = max(1, int(recompute_every))
        self._window: deque = deque(maxlen=int(window))
        self._lock = threading.Lock()
        self._ewma: Optional[float] = None
        self._mad_sigma = 0.0
        self._seen = 0
        self._since_recompute = 0
        self._anomalies = 0
        reg = registry if registry is not None else default_registry()
        self._counter = reg.counter(
            "zk_step_time_anomalies_total",
            help="straggler steps/dispatches flagged by the watchdog",
            labels={"stream": self.stream},
        )
        self._gauge = reg.gauge(
            "zk_step_time_ewma_ms",
            help="EWMA step/dispatch duration the watchdog baselines on",
            labels={"stream": self.stream},
        )

    @property
    def ewma_seconds(self) -> Optional[float]:
        return self._ewma

    @property
    def anomalies(self) -> int:
        return self._anomalies

    def _recompute_mad(self) -> None:
        # Median + MAD over the window; sorted() over <= window floats,
        # amortized to every Nth observe.
        vals = sorted(self._window)
        n = len(vals)
        if n < 4:
            self._mad_sigma = 0.0
            return
        med = (
            vals[n // 2]
            if n % 2
            else 0.5 * (vals[n // 2 - 1] + vals[n // 2])
        )
        devs = sorted(abs(v - med) for v in vals)
        mad = (
            devs[n // 2]
            if n % 2
            else 0.5 * (devs[n // 2 - 1] + devs[n // 2])
        )
        self._mad_sigma = _MAD_SIGMA * mad

    def observe(self, seconds: float, step: Optional[int] = None) -> bool:
        """Feed one duration; returns whether it was flagged."""
        seconds = float(seconds)
        if seconds < 0:
            return False
        with self._lock:
            self._seen += 1
            ewma = self._ewma
            anomalous = (
                self._seen > self.warmup
                and ewma is not None
                and seconds > ewma * self.min_ratio
                and seconds > ewma + self.threshold * self._mad_sigma
                and seconds - ewma >= self.min_excess_s
            )
            # The anomalous sample still feeds baseline + window (the
            # bounded-burst policy in the module docstring).
            self._ewma = (
                seconds
                if ewma is None
                else ewma + self.alpha * (seconds - ewma)
            )
            self._window.append(seconds)
            self._since_recompute += 1
            if self._since_recompute >= self._recompute_every:
                self._since_recompute = 0
                self._recompute_mad()
            if anomalous:
                self._anomalies += 1
            baseline = self._ewma
        self._gauge.set(baseline * 1e3)
        if anomalous:
            self._counter.inc()
            _trace.event(
                "step_time_anomaly",
                step=step,
                attrs={
                    "stream": self.stream,
                    "observed_ms": round(seconds * 1e3, 3),
                    "baseline_ms": round((ewma or 0.0) * 1e3, 3),
                },
            )
            # Flight-recorder trigger + the caller's seam, both outside
            # the lock and both failure-isolated from the step path.
            _recorder.notify(
                "step_time_anomaly",
                step=step,
                attrs={
                    "stream": self.stream,
                    "observed_ms": round(seconds * 1e3, 3),
                    "baseline_ms": round((ewma or 0.0) * 1e3, 3),
                },
            )
            callback = self.on_anomaly
            if callback is not None:
                try:
                    callback(self.stream, seconds, step)
                except Exception:
                    logger.warning(
                        "watchdog on_anomaly callback failed",
                        exc_info=True,
                    )
        return anomalous
