"""Hardware peak anchors: the ONE table both bench.py and the live
MFU gauges divide by.

MFU is only meaningful relative to a stated roofline, and the repo
already learned (BASELINE.md rounds 2-5) that the roofline itself is
the easiest number to get wrong: above-physics "measured" peaks from
remote-execution caches, generation-specific int8 factors, datasheet
clamps. All of that machinery lived in ``bench.py``; the device-side
performance ledger (``observability.ledger``) needs the SAME anchors
for its ``zk_train_mfu`` / ``zk_serve_mfu`` gauges — two copies would
inevitably diverge and the acceptance contract ("the live gauge agrees
with the offline bench within 10% on the same workload") would rot.
So the tables, the datasheet clamp, and the agreement-gated attempt
aggregation live HERE; ``bench.py`` re-exports them unchanged.

Two anchor-resolution paths, deliberately different:

- **bench.py** (offline, owns the device for minutes): measures the
  peak on-chip (matmul chains, marginal timing) and only falls back to
  the tables when measurement fails — ``resolve_peak_flops``.
- **live gauges** (a training/serving process): must never burn device
  time on calibration matmuls, so :func:`reference_peak_flops` resolves
  env override > datasheet-derived achievable peak (0.93x — the v5e's
  measured fraction of its datasheet, the transfer prior bench.py
  already uses) > the recorded v5e measurement. On a v5e this equals
  bench's measured anchor to within measurement noise; on other
  generations both sides use the same 0.93x prior — which is what keeps
  the live and offline MFU numbers comparable (docs/DESIGN.md §14).
"""

import logging
import math
import os
from typing import Optional, Tuple

logger = logging.getLogger(__name__)


def _env_peak(env, name: str) -> Optional[float]:
    """A positive-float env override, or None — a malformed value is
    warn-and-ignored, never raised: these resolve inside gauge updates
    on the training/serving hot paths, whose totality contract
    (docstrings below) a typo'd export must not be able to break."""
    raw = env.get(name)
    if not raw:
        return None
    try:
        value = float(raw)
    except (TypeError, ValueError):
        logger.warning(
            "%s=%r is not a number — ignoring the override", name, raw
        )
        return None
    if not math.isfinite(value) or value <= 0:
        logger.warning(
            "%s=%r is not a finite positive peak — ignoring the override",
            name,
            raw,
        )
        return None
    return value

__all__ = [
    "ACHIEVABLE_FRACTION",
    "BF16_PEAK_FALLBACK",
    "DATASHEET_HEADROOM",
    "HBM_BANDWIDTH_FALLBACK",
    "INT8_FACTOR_UPPER_BOUND",
    "INT8_PEAK_FALLBACK",
    "TPU_DATASHEET_BF16_TFLOPS",
    "TPU_DATASHEET_HBM_GBPS",
    "TPU_INT8_FACTOR",
    "V5E_KEYS",
    "aggregate_peak_attempts",
    "check_peak_against_datasheet",
    "datasheet_bf16_peak",
    "datasheet_hbm_bandwidth",
    "datasheet_match",
    "reference_hbm_bandwidth",
    "reference_int8_peak_flops",
    "reference_peak_flops",
]

# Fallback bf16 peak when on-chip measurement is unavailable: measured on
# this machine's v5e chip (BASELINE.md round-2 re-measurement: on-device
# fori_loop, full-sum dependency, 4096^3 bf16 matmul -> 184 TFLOP/s, 93%
# of the v5e datasheet 197). Round 1's 79 TFLOP/s was a dispatch-bound
# under-measurement.
BF16_PEAK_FALLBACK = 184e12

# Public datasheet bf16 peaks (TFLOP/s per chip) keyed by substrings of
# jax's ``device_kind`` string. A MEASURED peak above ~1.05x the matching
# datasheet number is physically impossible and therefore a measurement
# failure (remote-execution caching is the proven mechanism: rounds 2-4
# recorded 268 / 270 / 237.9 TF/s on a 197 TF/s v5e), never hardware.
# Longest-substring match so "v5 lite" wins over a bare "v5".
TPU_DATASHEET_BF16_TFLOPS = {
    "v2": 46.0,
    "v3": 123.0,
    "v4": 275.0,
    "v5 lite": 197.0,
    "v5litepod": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}

# Headroom above the datasheet number before a measurement is rejected:
# covers clock/rounding slop in the datasheet itself, not caching (which
# produces 1.2-1.4x errors, far outside this band).
DATASHEET_HEADROOM = 1.05

# Recorded v5e int8 MXU peak: measured on this machine with PRE-CAST
# int8 operands (the round-2 177 TOP/s carried an in-loop bf16 cast that
# halved it) — 4096^3 int8 dot_general chain, elementwise int32->int8
# squeeze between iterates, marginal timing: 369-373 TOP/s, ~94% of the
# 394 TOP/s datasheet (2x the bf16 197).
INT8_PEAK_FALLBACK = 369e12

# Per-generation int8-over-bf16 MXU rate: v5e/v5p/v6 double int8;
# v2/v3/v4 run int8 at the bf16 rate (no native int8 MXU doubling).
# Used both as the measurement ceiling (x DATASHEET_HEADROOM) and to
# scale the datasheet fallback — assuming 2x on a v4 would record a
# ~2x-understated MFU under an authoritative-sounding tag. Unknown
# generations use the 2x upper bound for the CLAMP only (permissive),
# never for a fallback value.
TPU_INT8_FACTOR = {
    "v2": 1.0,
    "v3": 1.0,
    "v4": 1.0,
    "v5 lite": 2.0,
    "v5litepod": 2.0,
    "v5e": 2.0,
    "v5p": 2.0,
    "v6 lite": 2.0,
    "v6e": 2.0,
}
INT8_FACTOR_UPPER_BOUND = 2.0

# Public datasheet HBM bandwidths (GB/s per chip), same substring-keyed
# table discipline as the bf16 peaks: the roofline the decode MBU gauge
# (memory-bound programs — docs/DESIGN.md §17) divides by. Deliberately
# the DATASHEET number with no "achievable fraction" prior: unlike the
# flops anchor, no on-chip bandwidth measurement has been recorded in
# this repo, and inventing a transfer fraction would be exactly the
# fabricated-anchor pathology rounds 2-5 document. A sustained-copy
# measurement can later join as a recorded fallback the way
# BF16_PEAK_FALLBACK did.
TPU_DATASHEET_HBM_GBPS = {
    "v2": 700.0,
    "v3": 900.0,
    "v4": 1228.0,
    "v5 lite": 819.0,
    "v5litepod": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
}

#: Fallback HBM bandwidth (bytes/s) when the generation is
#: unrecognized: the v5e datasheet number — the same fallback posture
#: as BF16_PEAK_FALLBACK (this machine's part).
HBM_BANDWIDTH_FALLBACK = 819e9

#: The v5e table keys: the generation whose RECORDED on-chip measurement
#: (BF16_PEAK_FALLBACK) exists, distinguished by key rather than by
#: comparing datasheet numbers (float identity would silently drift if a
#: table entry were corrected or two generations shared a number).
V5E_KEYS = frozenset({"v5 lite", "v5litepod", "v5e"})

#: The fraction of its datasheet peak a chip achieves on the bench's
#: measurement protocol — the v5e's measured 184/197, used as the
#: transfer prior for generations without a recorded measurement.
ACHIEVABLE_FRACTION = 0.93


def _match_datasheet_table(device_kind, table) -> Optional[Tuple[str, float]]:
    """Longest-substring table match shared by every datasheet lookup
    (flops AND bandwidth — one matching rule, so a future device_kind
    normalization cannot apply to one table and silently miss the
    other). Returns ``(table_key, raw_table_value)`` or None."""
    kind = (device_kind or "").lower()
    best = None
    for key, value in table.items():
        if key in kind and (best is None or len(key) > len(best[0])):
            best = (key, value)
    return best


def datasheet_match(device_kind) -> Optional[Tuple[str, float]]:
    """``(table_key, peak_flops)`` for the longest table key contained in
    ``device_kind``, or None when the generation is unrecognized."""
    best = _match_datasheet_table(device_kind, TPU_DATASHEET_BF16_TFLOPS)
    return None if best is None else (best[0], best[1] * 1e12)


def datasheet_bf16_peak(device_kind) -> Optional[float]:
    """Datasheet bf16 peak (FLOP/s) for a jax ``device_kind`` string, or
    None when the generation is unrecognized (future hardware must not be
    clamped to a stale table)."""
    match = datasheet_match(device_kind)
    return None if match is None else match[1]


def datasheet_hbm_bandwidth(device_kind) -> Optional[float]:
    """Datasheet HBM bandwidth (bytes/s) for a jax ``device_kind``
    string, or None when the generation is unrecognized — the same
    longest-substring matcher as :func:`datasheet_match`."""
    best = _match_datasheet_table(device_kind, TPU_DATASHEET_HBM_GBPS)
    return None if best is None else best[1] * 1e9


def reference_hbm_bandwidth(
    device_kind: Optional[str] = None, env=None
) -> Tuple[float, str]:
    """The HBM-bandwidth anchor for live MBU gauges (``zk_decode_mbu``),
    resolved WITHOUT touching the device — the bandwidth twin of
    :func:`reference_peak_flops`: ``ZK_BENCH_HBM_BANDWIDTH`` override
    (bytes/s) > the generation's datasheet bandwidth > the v5e
    fallback. Returns ``(bytes_per_sec, source_tag)``; resolution stays
    total even without jax/backends, so a gauge update can never raise
    (gauges publish -1 when the BYTES side is unknown, never because of
    this anchor)."""
    env = os.environ if env is None else env
    override = _env_peak(env, "ZK_BENCH_HBM_BANDWIDTH")
    if override is not None:
        return override, "env"
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = None
    sheet = datasheet_hbm_bandwidth(device_kind)
    if sheet is not None:
        return sheet, "datasheet"
    return HBM_BANDWIDTH_FALLBACK, "fallback_v5e"


def check_peak_against_datasheet(peak, device_kind) -> None:
    """Raise when a measured peak exceeds the datasheet band for this
    device generation — above-physics readings are measurement failures
    (the remote-execution-cache pathology), and recording one as
    "measured" corrupts the MFU time series (BENCH_r04: 237.9 TF/s on a
    197 TF/s v5e read as an MFU collapse). Unknown generations pass: a
    stale table must not reject a future chip."""
    sheet = datasheet_bf16_peak(device_kind)
    if sheet is not None and peak > DATASHEET_HEADROOM * sheet:
        raise ValueError(
            f"measured peak {peak / 1e12:.1f} TF/s exceeds the "
            f"{device_kind!r} datasheet {sheet / 1e12:.0f} TF/s by more "
            f"than {DATASHEET_HEADROOM:.2f}x — measurement failure "
            "(cached request?), not hardware"
        )


def aggregate_peak_attempts(attempts, rel_tol=0.05):
    """Agreement-gated aggregation of independent peak attempts: the
    estimate is the median of the largest cluster of attempts that agree
    within ``rel_tol`` (max/min <= 1+rel_tol over the cluster), requiring
    at least two members. Raises when no two attempts agree.

    This replaces max-over-attempts, whose design assumption — "noise can
    only make the chip look slower" — was empirically falsified three
    times (268, 270, 237.9 TF/s fast-side errors on a 197 TF/s part):
    max is precisely the aggregator that amplifies any residual fast-side
    failure mode. When two DISJOINT clusters tie for largest (a bimodal
    session — e.g. two jitter-degraded and two genuine attempts), neither
    is trustworthy and the function refuses rather than guess: anchoring
    on the slow cluster would INFLATE MFU (the round-2 114 TF/s lesson),
    anchoring on the fast one risks the cache pathology.
    """
    vals = sorted(a for a in attempts if a > 0)
    if len(vals) < 2:
        raise ValueError(
            f"need >=2 positive attempts to agree, got {len(vals)} "
            f"from {list(attempts)}"
        )
    best = None
    ambiguous = False  # a DISJOINT equal-size cluster exists
    for i in range(len(vals)):
        j = i
        while j + 1 < len(vals) and vals[j + 1] <= vals[i] * (1 + rel_tol):
            j += 1
        size = j - i + 1
        if size >= 2:
            if best is None or size > best[0]:
                best, ambiguous = (size, i, j), False
            elif size == best[0] and i > best[2]:
                # Only windows sharing NO attempts with the best are a
                # second mode; an equal-size window that overlaps it
                # (e.g. a mild fast outlier within tol of the cluster's
                # max but not its min) is the same cluster shifted and
                # must not veto the measurement.
                ambiguous = True
    if best is None:
        raise ValueError(
            "no two peak attempts agree within "
            f"{rel_tol:.0%}: {[round(v / 1e12, 1) for v in vals]} TF/s — "
            "session too noisy to anchor MFU"
        )
    if ambiguous:
        raise ValueError(
            "ambiguous peak attempts (two disjoint equal-size clusters): "
            f"{[round(v / 1e12, 1) for v in vals]} TF/s — bimodal "
            "session, refusing to pick a cluster"
        )
    _, i, j = best
    cluster = vals[i : j + 1]
    mid = len(cluster) // 2
    if len(cluster) % 2:
        return cluster[mid]
    return 0.5 * (cluster[mid - 1] + cluster[mid])


def reference_peak_flops(
    device_kind: Optional[str] = None, env=None
) -> Tuple[float, str]:
    """The bf16 peak anchor for LIVE MFU gauges, resolved WITHOUT
    touching the device: ``ZK_BENCH_PEAK_FLOPS`` override > the
    generation's datasheet peak scaled by the achievable fraction >
    the recorded v5e measurement. Returns ``(peak_flops, source_tag)``.

    A live process must never run calibration matmuls (they would steal
    step/dispatch time from the workload being measured), so this is
    deliberately table-driven where ``bench.resolve_peak_flops``
    measures. The two agree by construction: on a v5e the recorded
    measurement IS 0.93x of datasheet; elsewhere both sides apply the
    same 0.93x prior (bench's fallback path) or bench's fresh
    measurement lands within a few percent of it — inside the 10%
    live-vs-offline agreement contract (docs/DESIGN.md §14).

    ``device_kind`` defaults to the first jax device's kind; resolution
    stays total even when jax/backends are unavailable (the v5e
    fallback), so a gauge update can never raise.
    """
    env = os.environ if env is None else env
    override = _env_peak(env, "ZK_BENCH_PEAK_FLOPS")
    if override is not None:
        return override, "env"
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = None
    match = datasheet_match(device_kind)
    if match is not None:
        if match[0] in V5E_KEYS:
            # The recorded on-chip measurement exists for this part.
            return BF16_PEAK_FALLBACK, "v5e_measured"
        return ACHIEVABLE_FRACTION * match[1], "datasheet_scaled"
    return BF16_PEAK_FALLBACK, "fallback_v5e"


def reference_int8_peak_flops(
    device_kind: Optional[str] = None, env=None
) -> Tuple[float, str]:
    """Int8-MXU anchor for live gauges, same resolution discipline as
    :func:`reference_peak_flops` (``ZK_BENCH_INT8_PEAK_FLOPS``
    overrides); the datasheet path scales by the generation's
    int8-over-bf16 factor (1x on v2-v4)."""
    env = os.environ if env is None else env
    override = _env_peak(env, "ZK_BENCH_INT8_PEAK_FLOPS")
    if override is not None:
        return override, "env"
    if device_kind is None:
        try:
            import jax

            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = None
    match = datasheet_match(device_kind)
    if match is not None:
        if match[0] in V5E_KEYS:
            return INT8_PEAK_FALLBACK, "v5e_measured"
        factor = TPU_INT8_FACTOR.get(match[0], 1.0)
        return ACHIEVABLE_FRACTION * factor * match[1], "datasheet_scaled"
    return INT8_PEAK_FALLBACK, "fallback_v5e"
