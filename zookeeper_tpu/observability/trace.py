"""Host-side span/event tracing: the timeline the device trace can't see.

``jax.profiler`` answers "where does DEVICE time go" (xplane protobufs,
``training.profiling``); nothing answered "where does HOST time go" —
data wait vs slab dispatch vs metrics readback vs checkpoint drain vs
batcher coalescing — or correlated those phases ACROSS subsystems
(training thread, async checkpoint writer, micro-batcher worker,
checkpoint watcher). This module is that layer:

- :func:`span` — ``with span("data_wait", step=n): ...`` records one
  timed interval on the calling thread into a process-global tracer.
- :func:`event` — an instant marker (a fault injection firing, a
  request enqueue, a restart attempt).
- :func:`export_chrome_trace` — writes the ring as Chrome trace-event
  JSON, so the host timeline opens in Perfetto/``chrome://tracing``
  ALONGSIDE the device xplane view: load both, line up the wall clocks,
  and a stalled slab dispatch is attributable to the exact host phase
  that blocked it (docs/DESIGN.md §13).

Cost contract (the instrumented call sites are hot loops):

- **Disabled** (the default): ``span()``/``event()`` perform ONE module
  global read and return a shared no-op — no allocation, no lock, no
  clock read. The fixed keyword signature matters: a ``**kwargs``
  catch-all would allocate a dict on every call even when disabled.
- **Enabled**: one small object + two ``perf_counter_ns`` reads per
  span, appended to a bounded ``deque`` ring (thread-safe under the
  GIL; old records are evicted, never blocking a recorder). Measured
  end-to-end overhead on the training-step anchor is the bench's
  ``ZK_BENCH_OBS=1`` leg, budgeted at <= 2%.

Records carry thread identity + name (satellite: every background
thread here is ``zk-``-prefixed named) and optional ``step``/``slab``
attribution so a span is traceable to the training-loop coordinate
that produced it.

Request-scoped flow (docs/DESIGN.md §16): records may additionally
carry a ``rid`` — the monotonically-minted request id from
``observability.requests`` — and the Chrome exporter synthesizes flow
events (``s``/``t``/``f`` phases keyed on the rid) from every
rid-tagged record, so Perfetto draws one arrow from the submitting
thread through the batcher/decode worker to the dispatch span and the
completion. The rid rides the SAME record tuple (one extra slot), so
tagging costs nothing beyond the span/event itself.
"""

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "Tracer",
    "disable",
    "enable",
    "enabled",
    "event",
    "export_chrome_trace",
    "get_tracer",
    "install",
    "span",
    "to_chrome_trace",
]

#: Default ring capacity: ~64k records covers minutes of slab-cadence
#: training or tens of thousands of serving requests at a few MB of
#: host memory.
DEFAULT_CAPACITY = 65536


class _NoopSpan:
    """The shared disabled-path context manager: entering/exiting it
    allocates nothing."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False


_NOOP = _NoopSpan()


class _Span:
    """One live span: records its interval on ``__exit__``."""

    __slots__ = (
        "_tracer", "_name", "_step", "_slab", "_attrs", "_rid", "_t0",
    )

    def __init__(self, tracer, name, step, slab, attrs, rid):
        self._tracer = tracer
        self._name = name
        self._step = step
        self._slab = slab
        self._attrs = attrs
        self._rid = rid
        self._t0 = 0

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc: Any) -> bool:
        t1 = time.perf_counter_ns()
        thread = threading.current_thread()
        self._tracer._ring.append(
            (
                "X",
                self._name,
                self._t0,
                t1 - self._t0,
                thread.ident,
                thread.name,
                self._step,
                self._slab,
                self._attrs,
                self._rid,
            )
        )
        return False


class Tracer:
    """Thread-safe bounded ring of span/event records.

    Appends go straight into a ``deque(maxlen=capacity)`` — atomic
    under the GIL, evicting the oldest record when full, so recorders
    never block and memory is bounded by construction. ``drain()`` and
    the exporters snapshot the ring without stopping recording.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1.")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)

    def span(self, name, step=None, slab=None, attrs=None, rid=None) -> _Span:
        return _Span(self, name, step, slab, attrs, rid)

    def event(self, name, step=None, attrs=None, rid=None) -> None:
        thread = threading.current_thread()
        self._ring.append(
            (
                "i",
                name,
                time.perf_counter_ns(),
                0,
                thread.ident,
                thread.name,
                step,
                None,
                attrs,
                rid,
            )
        )

    def __len__(self) -> int:
        return len(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    def drain(self) -> List[dict]:
        """Snapshot-and-clear the ring as a list of dicts (oldest
        first). Recording may continue concurrently; records appended
        after the snapshot stay in the ring."""
        raw = list(self._ring)
        # Remove exactly the snapshotted records, identified by object
        # identity (``raw`` holds the references, so ids are stable).
        # A blind popleft-N would miscount when the ring is at capacity
        # and a concurrent append evicts a snapshotted record from the
        # left: the Nth popleft would then swallow the brand-new
        # UN-snapshotted record.
        snapshotted = {id(rec) for rec in raw}
        while True:
            try:
                head = self._ring[0]
            except IndexError:
                break
            if id(head) not in snapshotted:
                break
            try:
                self._ring.popleft()
            except IndexError:  # pragma: no cover - concurrent clear
                break
        return self._as_dicts(raw)

    def snapshot(self) -> List[dict]:
        """The current ring as dicts, oldest first, without clearing."""
        return self._as_dicts(list(self._ring))

    @staticmethod
    def _as_dicts(records) -> List[dict]:
        return [
            {
                "phase": ph,
                "name": name,
                "ts_ns": ts,
                "dur_ns": dur,
                "thread_id": tid,
                "thread_name": tname,
                "step": step,
                "slab": slab,
                "attrs": attrs,
                "rid": rid,
            }
            for (
                ph, name, ts, dur, tid, tname, step, slab, attrs, rid,
            ) in records
        ]


#: The process-global tracer; None = disabled (the single flag the hot
#: paths read).
_TRACER: Optional[Tracer] = None


def enable(capacity: int = DEFAULT_CAPACITY) -> Tracer:
    """Turn tracing on. Idempotent, first-enable-wins: when a tracer is
    already live, its ring is KEPT and ``capacity`` is ignored — a
    nested enabler (an experiment's ``trace_export`` inside an
    externally-traced session) must never drop the outer session's
    records or invalidate its ``get_tracer()`` reference. To change
    capacity, ``disable()`` first."""
    global _TRACER
    if _TRACER is None:
        _TRACER = Tracer(capacity)
    return _TRACER


def disable() -> None:
    global _TRACER
    _TRACER = None


def install(tracer: Optional[Tracer]) -> None:
    """Install ``tracer`` as the process-global tracer (None disables).
    This is the save/restore primitive for scoped measurements (the
    bench's tracing-overhead leg): ``saved = get_tracer(); ...;
    install(saved)`` puts back the ORIGINAL object with its ring
    intact, where a disable()/enable() cycle would swap in an empty
    ring and orphan held references. Normal code uses
    :func:`enable`/:func:`disable`."""
    global _TRACER
    _TRACER = tracer


def enabled() -> bool:
    return _TRACER is not None


def get_tracer() -> Optional[Tracer]:
    return _TRACER


def span(name: str, step=None, slab=None, attrs=None, rid=None):
    """A timed interval on the calling thread. Returns the shared no-op
    when tracing is disabled — one global read, zero allocation (the
    cost contract the hot loops rely on). ``attrs`` is an optional
    pre-built dict; build it only behind an ``enabled()`` check if its
    construction is itself nontrivial. ``rid`` tags the record with a
    request id (``observability.requests``) so the Chrome exporter can
    draw its cross-thread flow arrow."""
    tracer = _TRACER
    if tracer is None:
        return _NOOP
    return tracer.span(name, step, slab, attrs, rid)


def event(name: str, step=None, attrs=None, rid=None) -> None:
    """An instant marker (fault injection, enqueue, restart...). Free
    when disabled, same contract as :func:`span`."""
    tracer = _TRACER
    if tracer is not None:
        tracer.event(name, step, attrs, rid)


# -- Chrome trace-event export -------------------------------------------


def to_chrome_trace(tracer: Optional[Tracer] = None) -> Dict[str, Any]:
    """Render the ring as a Chrome trace-event JSON object
    (``{"traceEvents": [...]}``, the format Perfetto /
    ``chrome://tracing`` load natively).

    Spans become ``"X"`` (complete) events with microsecond ``ts`` /
    ``dur``; instants become ``"i"`` events; each thread gets an ``"M"``
    ``thread_name`` metadata event so the timeline rows carry the
    ``zk-``-prefixed thread names instead of bare ids. ``step``/``slab``
    attribution and attrs land in ``args`` (visible in the Perfetto
    detail pane). Timestamps are ``perf_counter_ns``-based — the same
    monotonic clock within one process, so host spans from every thread
    share one timeline.

    Rid-tagged records additionally synthesize Chrome FLOW events
    (docs/DESIGN.md §16): per rid with two or more records, the
    timeline-ordered chain gets ``s`` (start) / ``t`` (step) / ``f``
    (end) flow phases, ``id`` = the rid, ``cat`` = ``"rid"``, each flow
    point timestamped INSIDE its record (mid-span for ``X`` records) so
    Perfetto binds it to the enclosing slice (``bp: "e"``) and draws
    one arrow from the submitting thread through the worker's dispatch
    to the completion.
    """
    tracer = tracer if tracer is not None else _TRACER
    records = tracer.snapshot() if tracer is not None else []
    pid = os.getpid()
    events: List[dict] = []
    seen_threads: Dict[int, str] = {}
    flows: Dict[Any, List[dict]] = {}
    for rec in records:
        tid = rec["thread_id"]
        if tid not in seen_threads:
            seen_threads[tid] = rec["thread_name"]
            events.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": rec["thread_name"]},
                }
            )
        args = dict(rec["attrs"] or {})
        if rec["step"] is not None:
            args["step"] = rec["step"]
        if rec["slab"] is not None:
            args["slab"] = rec["slab"]
        rid = rec.get("rid")
        if rid is not None:
            args["rid"] = rid
        out = {
            "ph": rec["phase"],
            "name": rec["name"],
            "pid": pid,
            "tid": tid,
            "ts": rec["ts_ns"] / 1e3,
            "args": args,
        }
        if rec["phase"] == "X":
            out["dur"] = rec["dur_ns"] / 1e3
        else:
            out["s"] = "t"  # instant scoped to its thread
        events.append(out)
        if rid is not None:
            # Flow point INSIDE the record: mid-span for X so the point
            # falls within the slice Perfetto binds the arrow to.
            flows.setdefault(rid, []).append(
                {
                    "tid": tid,
                    "ts": (rec["ts_ns"] + rec["dur_ns"] // 2) / 1e3,
                }
            )
    for rid, points in flows.items():
        if len(points) < 2:
            continue  # an arrow needs two ends
        points.sort(key=lambda p: p["ts"])
        last = len(points) - 1
        for i, point in enumerate(points):
            ph = "s" if i == 0 else ("f" if i == last else "t")
            flow = {
                "ph": ph,
                "name": "request",
                "cat": "rid",
                "id": rid,
                "pid": pid,
                "tid": point["tid"],
                "ts": point["ts"],
            }
            if ph != "s":
                flow["bp"] = "e"  # bind to the enclosing slice
            events.append(flow)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(
    path: str, tracer: Optional[Tracer] = None
) -> int:
    """Write :func:`to_chrome_trace` to ``path``; returns the number of
    trace events written (metadata rows included)."""
    doc = to_chrome_trace(tracer)
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(doc["traceEvents"])
