"""Anomaly-triggered flight recorder: dump the evidence while it exists.

The live endpoints (``/metrics``, ``/statusz``, ``/trace``) answer
questions an operator is asking RIGHT NOW; an anomaly at 3am is
forensically dead by the time anyone scrapes — the trace ring has
evicted the bad request's spans and the gauges have moved on. The
``FlightRecorder`` closes that gap (docs/DESIGN.md §16): trigger
sources fire :func:`notify` the moment something goes wrong —

- ``StepTimeWatchdog`` anomalies (the ``on_anomaly`` callback seam),
- ``recompile_detected`` (both serving engines' post-warmup watermark),
- ``worker_crash`` / ``decode_worker_crash`` (batcher + scheduler
  crash cleanup),
- NaN-halt (``nan_policy="halt"`` raising ``NonFiniteLossError``),
- every ``fault_injected{kind}`` (chaos legs self-document),
- supervisor restarts (one bundle per recovery),
- manual ``POST /debugz`` (``ObservabilityServer``),

— and the recorder writes a self-contained BUNDLE directory joining
every observability layer into one artifact:

- ``trace.json`` — the trace ring as Chrome trace-event JSON, read via
  the non-destructive ``Tracer.snapshot()`` (``drain()`` stays reserved
  for the final teardown export; a bundle must never steal records
  from a concurrent ``/trace`` scrape),
- ``metrics.prom`` — full Prometheus text exposition of the attached
  registries,
- ``programs.json`` — the program ledger's table,
- ``statusz.json`` — every ``/statusz`` section the service exposes,
- ``requestlog.json`` — the per-service ``RequestLog`` tails (the rid
  of the request that crashed IS in here, correlating with its flow
  events in ``trace.json``),
- ``manifest.json`` — the trigger record (kind/step/attrs), the
  injected wall-clock source's timestamp (no traced code reads the
  wall clock — the ``clock`` parameter is the one source), and build
  provenance via ``bench.bench_metadata()`` (git sha + dirty flag).

Discipline: triggers are RATE-LIMITED (default >= 30 s between
bundles; a crash loop must not fill the disk) and retention is BOUNDED
(keep the last ``keep`` bundles, oldest deleted). Trigger call sites
sit on crash/alert paths, so ``trigger()`` never raises and, by
default, hands the write to a ``zk-flight-recorder`` daemon thread —
a worker-crash handler holding its scheduler lock is never stalled by
disk IO. ``synchronous=True`` (tests, and the ``/debugz`` manual
trigger) writes inline and returns the bundle path.

With no recorder installed, every :func:`notify` call site costs ONE
module-global read — the same zero-cost-until-opted-in contract as
``trace`` and ``faults``.
"""

import json
import logging
import os
import re
import shutil
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

from zookeeper_tpu.observability import trace as _trace
from zookeeper_tpu.observability.export import render_prometheus
from zookeeper_tpu.observability.registry import default_registry

__all__ = [
    "FlightRecorder",
    "arm",
    "disarm",
    "get_recorder",
    "install",
    "notify",
    "uninstall",
]

logger = logging.getLogger(__name__)

#: Bundle directory name: ``bundle-<seq>-<kind>`` — seq zero-padded so
#: lexicographic order IS trigger order (retention walks it).
_BUNDLE_RE = re.compile(r"^bundle-(\d{6})-")
_KIND_SAFE = re.compile(r"[^a-zA-Z0-9_.-]")


class FlightRecorder:
    """Writes rate-limited, bounded-retention debug bundles (see module
    docstring).

    ``registries`` render into ``metrics.prom``; ``status_providers``
    (section name -> zero-arg callable) build ``statusz.json``;
    ``request_logs`` (name -> :class:`RequestLog`) dump their tails.
    ``clock`` is THE wall-clock source (injected — traced code never
    reads wall time itself); rate limiting uses the monotonic clock.
    """

    def __init__(
        self,
        directory: str,
        *,
        registries: Sequence[Any] = (),
        status_providers: Optional[Mapping[str, Callable[[], Any]]] = None,
        request_logs: Optional[Mapping[str, Any]] = None,
        min_interval_s: float = 30.0,
        keep: int = 8,
        synchronous: bool = False,
        clock: Callable[[], float] = time.time,
    ) -> None:
        if min_interval_s < 0:
            raise ValueError(
                f"min_interval_s={min_interval_s} must be >= 0 (0 "
                "disables rate limiting)."
            )
        if keep < 1:
            raise ValueError(f"keep={keep} must be >= 1.")
        self.directory = str(directory)
        self.min_interval_s = float(min_interval_s)
        self.keep = int(keep)
        self.synchronous = bool(synchronous)
        self._clock = clock
        self._registries = list(registries)
        self._providers: Dict[str, Callable[[], Any]] = dict(
            status_providers or {}
        )
        self._request_logs: Dict[str, Any] = dict(request_logs or {})
        self._lock = threading.Lock()
        self._last_mono: Optional[float] = None
        # Seed the sequence from what is already on disk: a restarted
        # process (the crash-loop case this recorder exists for) must
        # extend the bundle series, not overwrite bundle-000001 — and
        # a fresh low seq sorting lexicographically oldest would have
        # _gc() delete the bundle it just wrote.
        self._seq = self._max_seq_on_disk()
        self._last_bundle: Optional[str] = None
        self._written = 0
        self._suppressed = 0
        self._queue: deque = deque()
        self._cv = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._inflight = False
        self._stop = threading.Event()

    # -- wiring (services attach their sections after construction) ------

    def add_status_provider(
        self, name: str, provider: Callable[[], Any]
    ) -> None:
        self._providers[str(name)] = provider

    def add_request_log(self, name: str, log: Any) -> None:
        self._request_logs[str(name)] = log

    def add_registry(self, registry: Any) -> None:
        self._registries.append(registry)

    # -- introspection ---------------------------------------------------

    @property
    def last_bundle(self) -> Optional[str]:
        """Path of the newest bundle written by THIS recorder."""
        return self._last_bundle

    @property
    def bundles_written(self) -> int:
        return self._written

    @property
    def bundles_suppressed(self) -> int:
        """Triggers swallowed by the rate limiter."""
        return self._suppressed

    def _max_seq_on_disk(self) -> int:
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        seqs = [
            int(m.group(1))
            for m in (_BUNDLE_RE.match(n) for n in names)
            if m is not None
        ]
        return max(seqs, default=0)

    def bundles(self) -> List[str]:
        """Bundle directories on disk, oldest first."""
        try:
            names = sorted(
                n
                for n in os.listdir(self.directory)
                if _BUNDLE_RE.match(n)
            )
        except OSError:
            return []
        return [os.path.join(self.directory, n) for n in names]

    # -- triggering ------------------------------------------------------

    def trigger(
        self,
        kind: str,
        *,
        step: Optional[int] = None,
        attrs: Optional[Mapping[str, Any]] = None,
        force: bool = False,
    ) -> Optional[str]:
        """Request one bundle for trigger ``kind``. Never raises (the
        call sites are crash/alert paths). Rate-limited unless
        ``force`` (the manual ``/debugz`` trigger). Returns the bundle
        path when written inline (``synchronous=True`` or ``force``),
        else None — the ``zk-flight-recorder`` thread writes it."""
        try:
            with self._lock:
                now = time.monotonic()
                if (
                    not force
                    and self._last_mono is not None
                    and self.min_interval_s > 0
                    and now - self._last_mono < self.min_interval_s
                ):
                    self._suppressed += 1
                    self._count("zk_flight_bundles_suppressed_total")
                    return None
                if not force:
                    # A forced (manual) bundle bypasses the limiter but
                    # must not ARM it: a /debugz poke right before a
                    # crash must not suppress the crash's bundle.
                    self._last_mono = now
                self._seq += 1
                seq = self._seq
            context = (seq, str(kind), step, dict(attrs or {}), self._clock())
            if self.synchronous or force:
                return self._write_guarded(context)
            with self._cv:
                self._queue.append(context)
                self._ensure_worker()
                self._cv.notify_all()
            return None
        except Exception:
            logger.warning("flight-recorder trigger failed", exc_info=True)
            return None

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until queued bundles are written (the deterministic
        wait the CI smoke and tests use). True = drained in time."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(0.05, remaining))
        return True

    def close(self) -> None:
        """Drain pending writes (best effort) and stop the writer
        thread. Safe to call repeatedly."""
        self.flush(timeout=5.0)
        self._stop.set()
        worker = self._worker
        if worker is not None:
            with self._cv:
                self._cv.notify_all()
            worker.join(timeout=5)
            self._worker = None
        self._stop.clear()

    # -- the writer ------------------------------------------------------

    def _ensure_worker(self) -> None:
        # Caller holds _cv.
        worker = self._worker
        if worker is None or not worker.is_alive():
            thread = threading.Thread(
                target=self._worker_loop,
                name="zk-flight-recorder",
                daemon=True,
            )
            self._worker = thread
            thread.start()

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            with self._cv:
                while not self._queue and not self._stop.is_set():
                    self._cv.wait(0.1)
                if self._stop.is_set() and not self._queue:
                    return
                context = self._queue.popleft()
                self._inflight = True
            try:
                self._write_guarded(context)
            finally:
                with self._cv:
                    self._inflight = False
                    self._cv.notify_all()

    def _write_guarded(self, context) -> Optional[str]:
        try:
            return self._write_bundle(*context)
        except Exception:
            logger.warning(
                "flight-recorder bundle write failed", exc_info=True
            )
            return None

    def _count(self, name: str, labels: Optional[Dict[str, str]] = None):
        try:
            default_registry().counter(
                name,
                help="flight-recorder bundle accounting",
                labels=labels,
            ).inc()
        except Exception:  # a registry conflict must not kill a trigger
            pass

    def _write_bundle(
        self,
        seq: int,
        kind: str,
        step: Optional[int],
        attrs: Dict[str, Any],
        t_wall: float,
    ) -> str:
        t0 = time.perf_counter()
        safe_kind = _KIND_SAFE.sub("_", kind) or "trigger"
        bundle_dir = os.path.join(
            self.directory, f"bundle-{seq:06d}-{safe_kind}"
        )
        os.makedirs(bundle_dir, exist_ok=True)

        def dump(name: str, payload: Any) -> str:
            path = os.path.join(bundle_dir, name)
            with open(path, "w") as f:
                json.dump(payload, f, default=str)
            return name

        files: List[str] = []
        # Trace ring: snapshot-based (never drain — a concurrent /trace
        # scrape and the teardown export must see the same records).
        files.append(dump("trace.json", _trace.to_chrome_trace()))
        prom_path = os.path.join(bundle_dir, "metrics.prom")
        with open(prom_path, "w") as f:
            f.write(
                render_prometheus(self._registries)
                if self._registries
                else ""
            )
        files.append("metrics.prom")
        try:
            from zookeeper_tpu.observability.ledger import default_ledger

            programs = default_ledger().as_status()
        except Exception as e:
            programs = {"error": repr(e)}
        files.append(dump("programs.json", programs))
        statusz: Dict[str, Any] = {
            "pid": os.getpid(),
            "threads": sorted(t.name for t in threading.enumerate()),
            "metrics": {},
        }
        for registry in self._registries:
            try:
                statusz["metrics"].update(registry.as_flat_dict())
            except Exception as e:
                statusz["metrics"][f"error:{id(registry)}"] = repr(e)
        for name, provider in self._providers.items():
            try:
                statusz[name] = provider()
            except Exception as e:  # one broken section, not no bundle
                statusz[name] = {"error": repr(e)}
        files.append(dump("statusz.json", statusz))
        files.append(
            dump(
                "requestlog.json",
                {
                    name: log.as_status(tail=256)
                    for name, log in self._request_logs.items()
                },
            )
        )
        # Provenance: which build wrote this (best effort — metadata
        # must never be the reason a bundle dies).
        try:
            import bench

            metadata = bench.bench_metadata()
        except Exception as e:
            metadata = {"error": repr(e)}
        manifest = {
            "bundle_format": 1,
            "seq": seq,
            "trigger": {"kind": kind, "step": step, "attrs": attrs},
            "time_unix": t_wall,
            "write_ms": round((time.perf_counter() - t0) * 1e3, 2),
            "metadata": metadata,
            "files": files,
        }
        # Manifest last: its presence marks the bundle complete (the
        # same finalize-ordering idea as the checkpoint protocol).
        dump("manifest.json", manifest)
        self._last_bundle = bundle_dir
        self._written += 1
        self._count("zk_flight_bundles_total", labels={"trigger": kind})
        self._gc()
        logger.warning(
            "flight recorder: bundle %s written (trigger=%s%s)",
            bundle_dir,
            kind,
            f", step={step}" if step is not None else "",
        )
        return bundle_dir

    def _gc(self) -> None:
        """Bounded retention: drop the oldest bundles beyond ``keep``."""
        bundles = self.bundles()
        for path in bundles[: max(0, len(bundles) - self.keep)]:
            shutil.rmtree(path, ignore_errors=True)


#: The process-global recorder; None = no recorder (the single flag
#: every trigger site reads).
_RECORDER: Optional[FlightRecorder] = None


def install(recorder: FlightRecorder) -> FlightRecorder:
    """Make ``recorder`` the process's flight recorder (replacing any
    prior one). Returns it for chaining."""
    global _RECORDER
    _RECORDER = recorder
    return recorder


def uninstall(recorder: Optional[FlightRecorder] = None) -> None:
    """Remove the global recorder. With ``recorder`` given, remove it
    only if it is still the installed one (a service tearing down must
    not evict a replacement another service already installed)."""
    global _RECORDER
    if recorder is None or _RECORDER is recorder:
        _RECORDER = None


def get_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def notify(
    kind: str,
    step: Optional[int] = None,
    attrs: Optional[Mapping[str, Any]] = None,
) -> None:
    """Fire a trigger at the installed recorder, if any. ONE global
    read when none is installed — the hook the trigger sources (fault
    injections, crash handlers, the watchdog, the supervisor) call
    unconditionally."""
    recorder = _RECORDER
    if recorder is not None:
        recorder.trigger(kind, step=step, attrs=attrs)


def arm(
    directory: str,
    *,
    registries: Sequence[Any] = (),
    status_providers: Optional[Mapping[str, Callable[[], Any]]] = None,
    request_logs: Optional[Mapping[str, Any]] = None,
    min_interval_s: float = 30.0,
    synchronous: bool = True,
) -> FlightRecorder:
    """Build-and-install in one step — the shared wiring the service
    configs and ``TrainingExperiment`` use, so the construction/install
    sequence cannot fork across them. Synchronous by default: a
    config-armed recorder's triggers are rare and the bundle should
    exist the moment the trigger returns (tests and the CI smoke rely
    on it)."""
    return install(
        FlightRecorder(
            directory,
            registries=registries,
            status_providers=status_providers,
            request_logs=request_logs,
            min_interval_s=min_interval_s,
            synchronous=synchronous,
        )
    )


def disarm(recorder: Optional[FlightRecorder]) -> None:
    """Teardown counterpart of :func:`arm`: evict the global slot only
    if ``recorder`` still owns it, then close its writer."""
    if recorder is not None:
        uninstall(recorder)
        recorder.close()
