"""Request-scoped flow tracing: rid minting + the per-service RequestLog.

The aggregate view (spans, typed metrics, the program ledger) answers
"how is the service doing"; nothing answered "what happened to THAT
request" — the question a p99 spike or a chaos leg actually poses.
This module adds the per-request axis (docs/DESIGN.md §16):

- :func:`next_rid` — a process-global, monotonically-assigned request
  id, minted once at ``MicroBatcher.submit`` / ``DecodeScheduler.submit``
  and carried on the request handle through queue → coalesce/slot-refill
  → dispatch → completion. Trace records tag it (``trace.span(...,
  rid=)``), and the Chrome exporter turns the chain into flow events so
  Perfetto draws one arrow from the submitting thread through the
  worker to the dispatch span.
- :class:`RequestLog` — a bounded per-service ring of one COMPACT
  summary per terminal request: rid, enqueue/dispatch/complete
  timestamps (``perf_counter_ns`` — the trace clock, so summaries and
  spans line up), bucket or slot, rows or tokens, outcome, and the
  weights step that served it. The ring is the "recent requests" table
  an operator reads off ``/statusz`` and the flight recorder dumps
  into every bundle — when the trace ring has already evicted a
  request's spans, its one-line summary survives here.

Cost contract: ``append`` is one dict build + one bounded ``deque``
append (GIL-atomic, never blocks, oldest evicted) — it rides the same
<= 2% observability budget as the trace spans, measured by the
``ZK_BENCH_OBS=1`` bench leg.
"""

import itertools
import threading
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "OUTCOMES",
    "RequestLog",
    "next_rid",
]

#: Terminal outcomes a request summary may carry. "ok" covers every
#: successful finish (decode records the finer eos/length/capacity
#: reason in ``detail``); the rest are the §10 failure taxonomy.
OUTCOMES = ("ok", "shed", "deadline_expired", "crashed", "error")

#: Process-global monotonic rid source. ``next()`` on an
#: ``itertools.count`` is GIL-atomic, so minting costs one C call and
#: two submits can never share a rid.
_RIDS = itertools.count(1)


def next_rid() -> int:
    """Mint the next request id (process-global, monotonic, never
    reused)."""
    return next(_RIDS)


class RequestLog:
    """Bounded ring of per-request terminal summaries for ONE service.

    Appends are cheap and thread-safe (bounded deque, GIL-atomic);
    readers (``tail``, ``find``, ``as_status``) snapshot without
    blocking recorders. ``total`` counts every summary ever appended —
    the ring only bounds what is still READABLE.
    """

    def __init__(self, name: str, capacity: int = 1024) -> None:
        if capacity < 1:
            raise ValueError(f"capacity={capacity} must be >= 1.")
        self.name = str(name)
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._total = 0
        self._by_outcome: Dict[str, int] = {}

    def append(
        self,
        rid: int,
        outcome: str,
        *,
        enqueue_ns: Optional[int] = None,
        dispatch_ns: Optional[int] = None,
        complete_ns: Optional[int] = None,
        rows: Optional[int] = None,
        tokens: Optional[int] = None,
        bucket: Optional[int] = None,
        slot: Optional[int] = None,
        weights_step: Optional[int] = None,
        detail: Optional[str] = None,
        role: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Record one TERMINAL request (exactly once per request — the
        handles' first-transition-wins completion guarantees callers
        only reach this once). Returns the summary dict."""
        record: Dict[str, Any] = {
            "rid": int(rid),
            "outcome": str(outcome),
            "enqueue_ns": enqueue_ns,
            "dispatch_ns": dispatch_ns,
            "complete_ns": complete_ns,
        }
        if rows is not None:
            record["rows"] = int(rows)
        if tokens is not None:
            record["tokens"] = int(tokens)
        if bucket is not None:
            record["bucket"] = int(bucket)
        if slot is not None:
            record["slot"] = int(slot)
        if weights_step is not None:
            record["weights_step"] = int(weights_step)
        if detail is not None:
            record["detail"] = str(detail)
        if role is not None:
            # Which serving ROLE completed the dispatch (disaggregated
            # topologies: "prefill" / "transfer" / "decode"; single-mesh
            # schedulers record "decode"). A small CLOSED vocabulary by
            # construction — same posture as the PR 10 label-cardinality
            # guard, though this is a record field, never a metric
            # label.
            record["role"] = str(role)
        # Counters under the lock; the append itself is deque-atomic.
        with self._lock:
            self._total += 1
            self._by_outcome[record["outcome"]] = (
                self._by_outcome.get(record["outcome"], 0) + 1
            )
        self._ring.append(record)
        return record

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def total(self) -> int:
        """Summaries ever appended (>= ``len()``: eviction only bounds
        readability)."""
        return self._total

    def tail(self, n: int = 64) -> List[Dict[str, Any]]:
        """The newest ``n`` summaries, oldest-of-the-tail first."""
        n = int(n)
        if n <= 0:
            return []  # records[-0:] would be the WHOLE ring
        return list(self._ring)[-n:]

    def find(self, rid: int) -> Optional[Dict[str, Any]]:
        """The (newest) summary for ``rid`` still in the ring, or
        None."""
        for record in reversed(list(self._ring)):
            if record["rid"] == rid:
                return record
        return None

    def as_status(self, tail: int = 32) -> Dict[str, Any]:
        """The ``/statusz`` section: counts by outcome + the recent
        tail — the numbers an operator reads before digging into the
        trace."""
        with self._lock:
            by_outcome = dict(self._by_outcome)
            total = self._total
        return {
            "service": self.name,
            "capacity": self.capacity,
            "recorded_total": total,
            "by_outcome": by_outcome,
            "tail": self.tail(tail),
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._total = 0
            self._by_outcome.clear()
