"""Live export: Prometheus text rendering + a stdlib HTTP endpoint.

The registry (``observability.registry``) holds the numbers; this
module makes them scrapeable from a RUNNING process — the capability
the serving/training stack lacked (metrics previously existed only as
end-of-run JSON lines and TensorBoard files). Two pieces:

- :func:`render_prometheus` — text exposition format 0.0.4 (the format
  every Prometheus-compatible scraper speaks): ``# HELP``/``# TYPE``
  headers, counter/gauge samples, histogram ``_bucket{le=...}`` +
  ``_sum`` + ``_count`` series.
- :class:`ObservabilityServer` — a ``ThreadingHTTPServer`` on a daemon
  thread (``zk-obs-http``) serving:

  - ``/metrics`` — every instrument of every attached registry.
  - ``/statusz`` — one JSON object: uptime, pid, live thread names,
    trace state, the flat scalar view of the registries, plus any
    caller-provided status sections (engine compile counts, queue
    rows, ...).
  - ``/trace`` — the current host-span ring as Chrome trace-event JSON
    (save the response, open in Perfetto) when tracing is enabled.
    Reads through ``Tracer.snapshot()`` (non-destructive): concurrent
    scrapes, flight-recorder bundles and the teardown export all see
    the same ring — ``drain()`` stays reserved for the final teardown.
  - ``POST /debugz`` — the manual flight-recorder trigger: when a
    ``FlightRecorder`` is installed (``observability.recorder``),
    writes one bundle inline (rate limit bypassed — a human asked) and
    returns its path; 503 when none is installed.

Stdlib only, opt-in, and off the hot path by construction: scrapes
read instrument values under their per-instrument locks; recorders
never wait on HTTP. ``port=0`` binds an ephemeral port (tests/CI read
``server.port`` after ``start()``).
"""

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence

from zookeeper_tpu.observability import trace as _trace
from zookeeper_tpu.observability.registry import (
    Histogram,
    MetricsRegistry,
)

__all__ = ["ObservabilityServer", "render_prometheus"]

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def _sanitize(name: str) -> str:
    name = _NAME_BAD_CHARS.sub("_", name)
    if not _NAME_OK.match(name):
        name = "_" + name
    return name


def _fmt(v: float) -> str:
    f = float(v)
    if f != f:  # NaN
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(labels: Dict[str, str], extra: str = "") -> str:
    parts = [
        _sanitize(k) + '="' + _escape_label_value(str(v)) + '"'
        for k, v in sorted(labels.items())
    ]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_prometheus(
    registries: Sequence[MetricsRegistry],
) -> str:
    """Render every instrument of ``registries`` in Prometheus text
    exposition format 0.0.4. Names are sanitized to the metric-name
    charset. Label variants of one metric name (e.g. a gauge
    registered per split) are grouped under a SINGLE ``# HELP``/``#
    TYPE`` header with their samples contiguous — the parser rejects a
    second TYPE line for a name, which would fail the whole scrape."""
    groups: Dict[str, List[Any]] = {}
    for registry in registries:
        for inst in registry.collect():
            groups.setdefault(_sanitize(inst.name), []).append(inst)
    lines: List[str] = []
    for name, insts in groups.items():
        head = insts[0]
        if head.help:
            lines.append(f"# HELP {name} {head.help}")
        lines.append(f"# TYPE {name} {head.kind}")
        for inst in insts:
            if isinstance(inst, Histogram):
                # One locked read: +Inf bucket, _sum and _count must be
                # mutually consistent or the exposition is spec-invalid.
                cumulative, count, total = inst.collect_state()
                for bound, c in zip(inst.buckets, cumulative):
                    le = 'le="' + _fmt(bound) + '"'
                    lines.append(
                        f"{name}_bucket{_label_str(inst.labels, le)} {c}"
                    )
                le_inf = 'le="+Inf"'
                lines.append(
                    f"{name}_bucket{_label_str(inst.labels, le_inf)} "
                    f"{count}"
                )
                lines.append(
                    f"{name}_sum{_label_str(inst.labels)} {_fmt(total)}"
                )
                lines.append(
                    f"{name}_count{_label_str(inst.labels)} {count}"
                )
            else:
                lines.append(
                    f"{name}{_label_str(inst.labels)} {_fmt(inst.value)}"
                )
    return "\n".join(lines) + "\n"


class ObservabilityServer:
    """``/metrics`` + ``/statusz`` (+ ``/trace``) over stdlib HTTP.

    ``registries`` are rendered in order; ``status_providers`` is a
    mapping of section name -> zero-arg callable returning a
    JSON-serializable dict, merged into ``/statusz`` (a provider that
    raises contributes its error string instead of killing the scrape).
    """

    def __init__(
        self,
        registries: Sequence[MetricsRegistry],
        port: int = 0,
        host: str = "127.0.0.1",
        status_providers: Optional[
            Dict[str, Callable[[], Dict[str, Any]]]
        ] = None,
    ) -> None:
        self._registries = list(registries)
        self._requested_port = int(port)
        self._host = host
        self._providers = dict(status_providers or {})
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._t_start = time.time()

    @property
    def port(self) -> Optional[int]:
        """The bound port (reads back the ephemeral port under
        ``port=0``); None before ``start()``."""
        return (
            self._httpd.server_address[1]
            if self._httpd is not None
            else None
        )

    @property
    def url(self) -> Optional[str]:
        return (
            f"http://{self._host}:{self.port}"
            if self._httpd is not None
            else None
        )

    def add_status_provider(
        self, name: str, provider: Callable[[], Dict[str, Any]]
    ) -> None:
        self._providers[name] = provider

    def render_metrics(self) -> str:
        return render_prometheus(self._registries)

    def render_statusz(self) -> Dict[str, Any]:
        import os

        # One tracer read: a concurrent disable() between an enabled()
        # check and a len(get_tracer()) would be a None deref mid-scrape.
        tracer = _trace.get_tracer()
        status: Dict[str, Any] = {
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self._t_start, 3),
            "threads": sorted(t.name for t in threading.enumerate()),
            "trace_enabled": tracer is not None,
            "trace_spans_buffered": len(tracer) if tracer is not None else 0,
            "metrics": {},
        }
        # Flight-recorder vitals (docs/DESIGN.md §16): is the capture
        # mechanism armed, and where did the last bundle land.
        try:
            from zookeeper_tpu.observability import recorder as _recorder

            rec = _recorder.get_recorder()
            status["flight_recorder"] = (
                {
                    "installed": True,
                    "directory": rec.directory,
                    "bundles_written": rec.bundles_written,
                    "bundles_suppressed": rec.bundles_suppressed,
                    "last_bundle": rec.last_bundle,
                }
                if rec is not None
                else {"installed": False}
            )
        except Exception as e:  # a recorder bug must not 500 /statusz
            status["flight_recorder"] = {"error": repr(e)}
        for registry in self._registries:
            status["metrics"].update(registry.as_flat_dict())
        # The program ledger renders on EVERY statusz (docs/DESIGN.md
        # §14): which compiled programs exist, their FLOPs/memory, and
        # what compilation cost — the device-side complement of the
        # metric view. Import is local (export must stay importable
        # even if the ledger module grows heavier deps).
        try:
            from zookeeper_tpu.observability.ledger import default_ledger

            status["programs"] = default_ledger().as_status()
        except Exception as e:  # a ledger bug must not 500 /statusz
            status["programs"] = {"error": repr(e)}
        for name, provider in self._providers.items():
            try:
                status[name] = provider()
            except Exception as e:  # a broken provider must not 500 /statusz
                status[name] = {"error": repr(e)}
        return status

    def start(self) -> "ObservabilityServer":
        if self._thread is not None and self._thread.is_alive():
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # silence per-request stderr
                pass

            def _send(self, code, content_type, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            "text/plain; version=0.0.4; charset=utf-8",
                            server.render_metrics().encode(),
                        )
                    elif path == "/statusz":
                        self._send(
                            200,
                            "application/json",
                            json.dumps(server.render_statusz()).encode(),
                        )
                    elif path == "/trace":
                        doc = _trace.to_chrome_trace()
                        self._send(
                            200, "application/json", json.dumps(doc).encode()
                        )
                    elif path in ("/", "/healthz"):
                        self._send(200, "text/plain", b"ok\n")
                    else:
                        self._send(404, "text/plain", b"not found\n")
                except BrokenPipeError:  # scraper hung up mid-response
                    pass

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/debugz":
                        from zookeeper_tpu.observability import (
                            recorder as _recorder,
                        )

                        rec = _recorder.get_recorder()
                        if rec is None:
                            self._send(
                                503,
                                "application/json",
                                json.dumps(
                                    {
                                        "error": "no flight recorder "
                                        "installed (set "
                                        "flight_recorder_dir=)"
                                    }
                                ).encode(),
                            )
                            return
                        # force=True: a human asked — bypass the rate
                        # limit and write inline so the response can
                        # carry the bundle path.
                        bundle = rec.trigger(
                            "manual",
                            attrs={"source": "POST /debugz"},
                            force=True,
                        )
                        self._send(
                            200,
                            "application/json",
                            json.dumps({"bundle": bundle}).encode(),
                        )
                    else:
                        self._send(404, "text/plain", b"not found\n")
                except BrokenPipeError:
                    pass

        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="zk-obs-http",
            daemon=True,
        )
        self._t_start = time.time()
        self._thread.start()
        return self

    def stop(self) -> None:
        httpd, thread = self._httpd, self._thread
        self._httpd, self._thread = None, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5)
