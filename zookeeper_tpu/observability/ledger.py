"""The program ledger: per-executable XLA cost accounting for a live
process.

The host side became observable in the spans/registry layer
(docs/DESIGN.md §13), but the DEVICE side stayed a black box outside
manual ``jax.profiler`` captures: nothing could answer "what is this
process's MFU right now", "which compiled program owns the HBM", or
"did a recompile just stall serving" from a live endpoint. This module
closes that gap at the one place every executable passes through — the
lower/compile seam:

- :func:`cost_analysis_dict` / :func:`cost_flops` — the ONE
  ``cost_analysis()`` wrapper (``models.summary``, ``bench.py``, the
  serving engine and the partitioner seams all call it), tolerant of
  backends that return ``None``, a ``[dict]`` list, or a dict missing
  keys (the CPU backend does all three across jax versions).
- :class:`ProgramLedger` — a process-global, thread-safe record of
  every compiled program: identity key, FLOPs/bytes from XLA's own
  cost analysis, lower/compile wall time, and the compiled memory
  analysis (argument/output/temp bytes — which program owns the HBM).
  Every record also bumps ``zk_compiles_total{kind=}`` /
  ``zk_compile_ms_total{kind=}`` counters in the default registry and
  renders as a ``/statusz`` section (``observability.export``).
- :class:`LedgeredExecutable` — the partitioner seams' wrapper: the
  first call per argument signature does the AOT ``lower()`` +
  ``compile()`` explicitly (timed, ledger-recorded — the same work
  ``jax.jit`` would have done lazily, now visible), and every later
  call dispatches the compiled executable directly (one attribute read
  of steady-state overhead). An argument-shape change falls back to
  the wrapped ``jit`` callable, which retraces exactly as an
  uninstrumented seam would.
- :func:`mfu` — FLOPs/time/peak with total guards; the gauge math for
  ``zk_train_mfu`` / ``zk_serve_mfu`` (peaks from
  ``observability.peaks`` so the live gauges and bench.py divide by
  the same anchors).

Identity keys (docs/DESIGN.md §14): ``<kind>`` names the seam
(``train_step`` / ``multi_step`` / ``eval_step`` / ``serve_forward`` /
``summary_forward``), the key string appends the argument signature
(leaf count + a shape/dtype digest) and the mesh axis sizes — enough
to tell two programs apart in ``/statusz`` without dumping whole
pytree structures.
"""

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from zookeeper_tpu.observability import trace as _trace
from zookeeper_tpu.observability.registry import default_registry

__all__ = [
    "LedgeredExecutable",
    "ProgramLedger",
    "ProgramRecord",
    "cost_analysis_dict",
    "cost_bytes",
    "cost_flops",
    "default_ledger",
    "mbu",
    "mfu",
]


# -- the shared cost_analysis wrapper ------------------------------------


def cost_analysis_dict(program: Any) -> Dict[str, float]:
    """``program.cost_analysis()`` as a plain dict, or ``{}``.

    ``program`` is anything with a ``cost_analysis`` method (a jax
    ``Lowered`` or ``Compiled``). Every historical failure mode maps to
    ``{}`` instead of raising: backends that return ``None`` (CPU on
    some versions), the older ``[dict]`` list convention, a non-dict
    payload, or ``cost_analysis`` itself raising (interpret-mode
    Pallas, unsupported backends). Cost analysis is diagnostic — it
    must never be the reason a compile seam dies."""
    try:
        analysis = program.cost_analysis()
    except Exception:
        return {}
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return {}
    return analysis


def _scalar_from(analysis: Dict[str, Any], key: str) -> Optional[float]:
    value = analysis.get(key)
    try:
        value = float(value)
    except (TypeError, ValueError):
        return None
    # NaN/negative costs are backend noise, not information.
    return value if value == value and value >= 0 else None


def _analysis_scalar(program: Any, key: str) -> Optional[float]:
    return _scalar_from(cost_analysis_dict(program), key)


def cost_flops(program: Any) -> Optional[float]:
    """The executable's FLOP count per XLA's cost analysis, or None.
    For an SPMD executable this is the PER-DEVICE partitioned module's
    count (bench.py's long-standing convention — do not divide by the
    chip count again)."""
    return _analysis_scalar(program, "flops")


def cost_bytes(program: Any) -> Optional[float]:
    """Bytes accessed per XLA's cost analysis, or None."""
    return _analysis_scalar(program, "bytes accessed")


def memory_analysis_dict(compiled: Any) -> Dict[str, float]:
    """The compiled memory analysis as a plain dict (argument/output/
    temp/code bytes), or ``{}`` when the backend exposes none."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for name in (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "alias_size_in_bytes",
        "generated_code_size_in_bytes",
    ):
        value = getattr(mem, name, None)
        if isinstance(value, (int, float)):
            out[name] = float(value)
    return out


# -- the ledger ----------------------------------------------------------


@dataclass
class ProgramRecord:
    """One compiled program's ledger row."""

    kind: str
    key: str
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    lower_ms: Optional[float] = None
    compile_ms: Optional[float] = None
    memory: Dict[str, float] = field(default_factory=dict)
    attrs: Dict[str, Any] = field(default_factory=dict)
    #: Monotonic registration ordinal (process lifetime order).
    ordinal: int = 0
    #: Wall-clock registration time (time.time(); for /statusz only).
    recorded_at: float = 0.0
    dispatches: int = 0

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "kind": self.kind,
            "key": self.key,
            "ordinal": self.ordinal,
            "dispatches": self.dispatches,
        }
        if self.flops is not None:
            out["flops"] = self.flops
        if self.bytes_accessed is not None:
            out["bytes_accessed"] = self.bytes_accessed
        if self.lower_ms is not None:
            out["lower_ms"] = round(self.lower_ms, 3)
        if self.compile_ms is not None:
            out["compile_ms"] = round(self.compile_ms, 3)
        if self.memory:
            out["memory"] = dict(self.memory)
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        return out


class ProgramLedger:
    """Thread-safe, bounded record of every program this process
    compiled. Appends are cheap (compiles are rare by construction);
    readers snapshot under the lock. ``max_records`` bounds memory for
    pathological compile storms (the oldest rows are evicted — their
    counters survive in the registry totals)."""

    def __init__(self, max_records: int = 512, registry=None) -> None:
        self._lock = threading.Lock()
        self._records: List[ProgramRecord] = []
        self._max_records = int(max_records)
        self._ordinal = 0
        self._registry = registry

    def _reg(self):
        return self._registry if self._registry is not None else default_registry()

    def record(
        self,
        kind: str,
        key: str,
        *,
        lowered: Any = None,
        compiled: Any = None,
        lower_ms: Optional[float] = None,
        compile_ms: Optional[float] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> ProgramRecord:
        """Register one compiled program. FLOPs/bytes come from
        ``compiled`` when available (post-optimization numbers), else
        ``lowered``; memory analysis from ``compiled`` only. Never
        raises on analysis failure — the seam's compile must not."""
        source = compiled if compiled is not None else lowered
        # ONE cost pass per program: cost_analysis() re-runs XLA's HLO
        # cost analysis on every call, so extract both scalars from a
        # single invocation.
        analysis = cost_analysis_dict(source) if source is not None else {}
        rec = ProgramRecord(
            kind=str(kind),
            key=str(key),
            flops=_scalar_from(analysis, "flops"),
            bytes_accessed=_scalar_from(analysis, "bytes accessed"),
            lower_ms=lower_ms,
            compile_ms=compile_ms,
            memory=(
                memory_analysis_dict(compiled) if compiled is not None else {}
            ),
            attrs=dict(attrs or {}),
            recorded_at=time.time(),
        )
        with self._lock:
            self._ordinal += 1
            rec.ordinal = self._ordinal
            self._records.append(rec)
            if len(self._records) > self._max_records:
                del self._records[: len(self._records) - self._max_records]
        try:
            reg = self._reg()
            reg.counter(
                "zk_compiles_total",
                help="programs compiled (ledger-recorded), by seam kind",
                labels={"kind": rec.kind},
            ).inc()
            if compile_ms is not None:
                reg.counter(
                    "zk_compile_ms_total",
                    help="cumulative XLA compile wall time, by seam kind",
                    labels={"kind": rec.kind},
                ).inc(max(0.0, float(compile_ms)))
        except Exception:  # registry conflicts must not kill a compile
            pass
        if _trace.enabled():
            _trace.event(
                "program_compiled",
                attrs={
                    "kind": rec.kind,
                    "key": rec.key,
                    "compile_ms": (
                        round(compile_ms, 1) if compile_ms is not None else None
                    ),
                },
            )
        return rec

    def entries(self) -> List[ProgramRecord]:
        with self._lock:
            return list(self._records)

    def latest(
        self, kind: Optional[str] = None
    ) -> Optional[ProgramRecord]:
        """Newest record (of ``kind``, when given)."""
        with self._lock:
            for rec in reversed(self._records):
                if kind is None or rec.kind == kind:
                    return rec
        return None

    def total_compile_ms(self) -> float:
        with self._lock:
            return sum(r.compile_ms or 0.0 for r in self._records)

    def as_status(self) -> Dict[str, Any]:
        """The ``/statusz`` ledger section: per-program rows (newest
        first, capped) + totals."""
        with self._lock:
            records = list(self._records)
        return {
            "programs": [r.as_dict() for r in reversed(records)][:64],
            "count": len(records),
            "total_compile_ms": round(
                sum(r.compile_ms or 0.0 for r in records), 1
            ),
        }

    def clear(self) -> None:
        with self._lock:
            self._records.clear()


_DEFAULT = ProgramLedger()


def default_ledger() -> ProgramLedger:
    """The process-global ledger every seam records into (compiles are
    process-scarce events; one table is the point — ``/statusz``
    renders it whole)."""
    return _DEFAULT


# -- MFU gauge math ------------------------------------------------------


def mfu(
    flops: Optional[float],
    seconds: Optional[float],
    peak_flops: Optional[float],
) -> Optional[float]:
    """Model FLOPs utilization: ``flops / seconds / peak``. Returns
    None unless every input is a positive finite number — a gauge
    update must never raise, and a nonsense ratio (0-time, missing
    cost analysis) must render as "unknown" (the gauges publish -1),
    not as 0% or infinity."""
    try:
        flops, seconds, peak_flops = (
            float(flops),
            float(seconds),
            float(peak_flops),
        )
    except (TypeError, ValueError):
        return None
    if not (flops > 0 and seconds > 0 and peak_flops > 0):
        return None
    value = flops / seconds / peak_flops
    return value if value == value and value != float("inf") else None


def mbu(
    bytes_accessed: Optional[float],
    seconds: Optional[float],
    peak_bytes_per_sec: Optional[float],
) -> Optional[float]:
    """Memory-bandwidth utilization: ``bytes / seconds / bandwidth`` —
    the roofline lens for MEMORY-bound programs (decode_step reads the
    KV cache and weights every token; its MFU is meaninglessly low by
    construction). Same totality contract as :func:`mfu`: None unless
    every input is positive and finite, so the ``zk_decode_mbu`` gauge
    renders -1-unknown instead of raising or lying. NOTE the bytes side
    is XLA's STATIC cost analysis — with a length-aware kernel the true
    bytes read are lower, so the gauge is an upper bound
    (docs/DESIGN.md §17)."""
    return mfu(bytes_accessed, seconds, peak_bytes_per_sec)


# -- the compile-seam wrapper --------------------------------------------


def _signature(args) -> tuple:
    """Hashable (shape, dtype, sharding) signature of a call's
    arguments — the cache key deciding whether the AOT-compiled
    program fits. Sharding/placement is part of the signature because
    an AOT ``Compiled`` rejects re-placed arguments that a plain jit
    would silently reshard or retrace for."""
    import jax

    return tuple(
        (
            tuple(getattr(leaf, "shape", ())),
            str(getattr(leaf, "dtype", type(leaf).__name__)),
            str(getattr(leaf, "sharding", "")),
        )
        for leaf in jax.tree.leaves(args)
    )


class LedgeredExecutable:
    """Ledger-instrumented wrapper over a ``jax.jit`` callable.

    First call: ``lower()`` + ``compile()`` explicitly (both timed,
    recorded into the ledger with cost + memory analysis), then
    dispatch the compiled executable — the exact work the jit would
    have done lazily, now accounted. Steady state: one attribute read
    + one compiled dispatch per call (no signature recomputation — the
    overwhelmingly common case is a fixed-shape loop).

    A call whose arguments no longer match the compiled program (a
    partial final eval batch, a re-run at new shapes) raises from the
    compiled dispatch; the wrapper then falls back to the wrapped jit
    callable for that call and every future non-matching signature —
    identical behavior (and identical retrace cost) to the
    uninstrumented seam, minus ledger rows for the extra shapes.

    ``lower`` delegates to the wrapped jit (bench.py AOT-compiles
    through the seam itself); unknown attributes delegate too, so the
    wrapper is drop-in for callers that introspect the jitted object.
    """

    def __init__(
        self,
        jitted: Callable,
        *,
        kind: str,
        key: str,
        ledger: Optional[ProgramLedger] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        self._jitted = jitted
        self._kind = kind
        self._key = key
        self._ledger = ledger
        self._attrs = dict(attrs or {})
        self._compiled = None
        self._signature = None
        self.ledger_entry: Optional[ProgramRecord] = None

    def _ledger_obj(self) -> ProgramLedger:
        return self._ledger if self._ledger is not None else default_ledger()

    def _compile_first(self, args):
        import time as _time

        t0 = _time.perf_counter()
        lowered = self._jitted.lower(*args)
        t1 = _time.perf_counter()
        compiled = lowered.compile()
        t2 = _time.perf_counter()
        sig = _signature(args)
        entry = self._ledger_obj().record(
            self._kind,
            f"{self._key}/args{len(sig)}x{abs(hash(sig)) % 10**8:08d}",
            lowered=lowered,
            compiled=compiled,
            lower_ms=(t1 - t0) * 1e3,
            compile_ms=(t2 - t1) * 1e3,
            attrs=self._attrs,
        )
        self._signature = sig
        self.ledger_entry = entry
        self._compiled = compiled
        return compiled

    def __call__(self, *args):
        compiled = self._compiled
        if compiled is None:
            compiled = self._compile_first(args)
            entry = self.ledger_entry
            entry.dispatches += 1
            return compiled(*args)
        entry = self.ledger_entry
        try:
            out = compiled(*args)
        except (TypeError, ValueError):
            # Aval/sharding signature mismatch (jax raises TypeError for
            # differing argument types, ValueError for sharding/device
            # mismatches) — dispatch through the plain jit, which
            # reshards/retraces exactly like the uninstrumented seam.
            # Compiled argument checks run BEFORE donation, so the
            # arguments are intact. A signature (shape + dtype +
            # sharding) that DOES match the compiled program cannot
            # reach here: the same error would re-raise identically
            # from the jit fallback anyway.
            if _signature(args) == self._signature:
                raise  # same signature — a real error, not a re-spec
            return self._jitted(*args)
        if entry is not None:
            entry.dispatches += 1
        return out

    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)

    def __getattr__(self, name):
        # Fallback for introspection (only consulted when the attribute
        # is not on the wrapper itself).
        return getattr(self._jitted, name)
