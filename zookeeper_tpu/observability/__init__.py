"""Unified observability: host-side span tracing, typed metrics, live
export.

The cross-cutting layer the north star's "production under heavy
traffic" claim requires (docs/DESIGN.md §13). Three modules, stdlib
only, all zero-cost until opted in:

- ``trace`` — ``span()``/``event()`` into a bounded ring buffer with a
  Chrome trace-event exporter: host phases (data wait, slab dispatch,
  metrics readback, checkpoint write, batcher coalescing, preemption
  drain) open in Perfetto alongside the ``jax.profiler`` device trace.
- ``registry`` — Counter/Gauge/Histogram instruments behind a typed
  name table; ``ServingMetrics`` and the background subsystems record
  into it.
- ``export`` — Prometheus text exposition + a stdlib HTTP
  ``/metrics``-``/statusz``-``/trace`` endpoint
  (``TrainingExperiment.metrics_port`` / ``ServingConfig.metrics_port``
  opt in).

The device-side half (docs/DESIGN.md §14) rides the same substrate:

- ``ledger`` — the process-global program ledger: every lower/compile
  seam records identity key, XLA cost-analysis FLOPs/bytes, compile
  wall time and compiled memory analysis; feeds the ``zk_train_mfu`` /
  ``zk_serve_mfu`` gauges and a ``/statusz`` section.
- ``watchdog`` — EWMA+MAD step-time anomaly detection over the
  slab/step/dispatch duration streams (``step_time_anomaly`` /
  ``recompile_detected`` events + counters).
- ``device`` — the ``zk-device-probe`` ``memory_stats()`` poller
  behind the live ``zk_hbm_*`` per-device gauges.
- ``peaks`` — the hardware peak anchors (datasheet tables + the
  measured-peak aggregation) shared with ``bench.py`` so live and
  offline MFU divide by the same roofline.

The request-scoped half (docs/DESIGN.md §16) joins the layers:

- ``requests`` — monotone rid minting + the bounded per-service
  ``RequestLog`` of terminal request summaries; rids tag trace records
  and render as Chrome flow events.
- ``recorder`` — the anomaly-triggered ``FlightRecorder``: watchdog
  anomalies, recompiles, worker crashes, NaN-halts, fault injections
  and manual ``POST /debugz`` dump a rate-limited, bounded-retention
  bundle (trace ring + exposition text + ledger + statusz +
  RequestLog tails + manifest).
"""

from zookeeper_tpu.observability import trace
from zookeeper_tpu.observability.device import (
    DeviceProbe,
    device_memory_stats,
)
from zookeeper_tpu.observability.export import (
    ObservabilityServer,
    render_prometheus,
)
from zookeeper_tpu.observability.ledger import (
    LedgeredExecutable,
    ProgramLedger,
    cost_analysis_dict,
    cost_flops,
    default_ledger,
    mfu,
)
from zookeeper_tpu.observability.recorder import FlightRecorder
from zookeeper_tpu.observability.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from zookeeper_tpu.observability.requests import RequestLog, next_rid
from zookeeper_tpu.observability.trace import (
    Tracer,
    event,
    export_chrome_trace,
    span,
    to_chrome_trace,
)
from zookeeper_tpu.observability.watchdog import StepTimeWatchdog

__all__ = [
    "Counter",
    "DeviceProbe",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "LedgeredExecutable",
    "MetricsRegistry",
    "ObservabilityServer",
    "ProgramLedger",
    "RequestLog",
    "StepTimeWatchdog",
    "Tracer",
    "cost_analysis_dict",
    "cost_flops",
    "default_ledger",
    "default_registry",
    "device_memory_stats",
    "event",
    "export_chrome_trace",
    "mfu",
    "next_rid",
    "render_prometheus",
    "span",
    "to_chrome_trace",
    "trace",
]
