"""Unified observability: host-side span tracing, typed metrics, live
export.

The cross-cutting layer the north star's "production under heavy
traffic" claim requires (docs/DESIGN.md §13). Three modules, stdlib
only, all zero-cost until opted in:

- ``trace`` — ``span()``/``event()`` into a bounded ring buffer with a
  Chrome trace-event exporter: host phases (data wait, slab dispatch,
  metrics readback, checkpoint write, batcher coalescing, preemption
  drain) open in Perfetto alongside the ``jax.profiler`` device trace.
- ``registry`` — Counter/Gauge/Histogram instruments behind a typed
  name table; ``ServingMetrics`` and the background subsystems record
  into it.
- ``export`` — Prometheus text exposition + a stdlib HTTP
  ``/metrics``-``/statusz``-``/trace`` endpoint
  (``TrainingExperiment.metrics_port`` / ``ServingConfig.metrics_port``
  opt in).
"""

from zookeeper_tpu.observability import trace
from zookeeper_tpu.observability.export import (
    ObservabilityServer,
    render_prometheus,
)
from zookeeper_tpu.observability.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from zookeeper_tpu.observability.trace import (
    Tracer,
    event,
    export_chrome_trace,
    span,
    to_chrome_trace,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ObservabilityServer",
    "Tracer",
    "default_registry",
    "event",
    "export_chrome_trace",
    "render_prometheus",
    "span",
    "to_chrome_trace",
    "trace",
]
