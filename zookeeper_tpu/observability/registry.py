"""Typed metrics registry: Counter / Gauge / Histogram behind one name
table.

The repo grew three unrelated metric surfaces (training
``MetricsWriter`` scalars, the serving counter bag, async-checkpoint
``stats`` dicts); none of them could be *scraped* from a live process.
This registry is the common substrate: subsystems register typed
instruments once and record into them from any thread; an exporter
(``observability.export``) renders every registered series in one pass
— Prometheus text for ``/metrics``, a flat dict for ``/statusz`` and
the ``MetricsWriter`` family.

Semantics (the useful subset of the Prometheus data model):

- :class:`Counter` — monotone float/int total; ``inc(n)`` with n >= 0.
- :class:`Gauge` — a settable point-in-time value (``set``/``inc``).
- :class:`Histogram` — FIXED ascending bucket bounds declared at
  registration; ``observe(v)`` updates cumulative bucket counts +
  sum/count. Fixed buckets keep ``observe`` O(log buckets) with zero
  allocation — the recorder-side cost model serving needs — and render
  directly as Prometheus ``_bucket{le=...}`` series.

All instruments are lock-guarded (recorders race across the training
thread, batcher worker, checkpoint writer, watcher); registration is
get-or-create keyed on ``(name, labels)`` so two subsystems asking for
the same series share one instrument, while a same-name different-TYPE
registration fails loudly (a silent type fork would render invalid
exposition text).

Label-cardinality guard: one metric NAME may register at most
``max_label_variants`` distinct label-value combinations (default 64).
Beyond the cap a registration returns a DETACHED instrument — fully
usable by the caller, but never collected, so ``/metrics`` stays
bounded — while ``zk_labels_dropped_total{metric=<name>}`` counts the
drops and one WARNING names the runaway series. An unbounded label (a
future per-tenant or per-bucket label fed from request data) must
never grow the exposition without bound.
"""

import bisect
import logging
import math
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Set, Tuple

logger = logging.getLogger(__name__)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
]

#: Default latency-ish buckets (ms): sub-ms serving dispatches through
#: multi-second checkpoint writes.
DEFAULT_MS_BUCKETS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0,
)

#: Default ratio buckets (bucket fill / padding waste: values in [0, 1]).
DEFAULT_RATIO_BUCKETS = (
    0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 1.0,
)

_LabelsKey = Tuple[Tuple[str, str], ...]


def _labels_key(labels: Optional[Mapping[str, str]]) -> _LabelsKey:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


class _Instrument:
    kind = "untyped"

    def __init__(
        self,
        name: str,
        help: str = "",
        labels: Optional[Mapping[str, str]] = None,
    ) -> None:
        self.name = name
        self.help = help
        self.labels: Dict[str, str] = dict(labels or {})
        self._lock = threading.Lock()


class Counter(_Instrument):
    """Monotonically increasing total."""

    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(
                f"Counter {self.name!r} is monotone; inc({n}) is negative "
                "(use a Gauge for values that go down)."
            )
        with self._lock:
            self._value += n

    def reset(self) -> None:
        """Zero the total IN PLACE (the instrument object and its
        registry registration survive — scrapers see an ordinary
        counter reset, the same thing a process restart produces)."""
        with self._lock:
            self._value = 0.0

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Instrument):
    """Point-in-time value; settable from any thread."""

    kind = "gauge"

    def __init__(self, name, help="", labels=None, initial: float = 0.0):
        super().__init__(name, help, labels)
        self._initial = float(initial)
        self._value = float(initial)

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def reset(self) -> None:
        """Back to the registration-time ``initial`` value, in place."""
        with self._lock:
            self._value = self._initial

    @property
    def value(self) -> float:
        return self._value


class Histogram(_Instrument):
    """Cumulative histogram over fixed ascending bucket bounds.

    ``observe`` is the hot call: one bisect + two adds under the lock,
    no allocation. ``+Inf`` is implicit (the total count)."""

    kind = "histogram"

    def __init__(
        self,
        name,
        buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
        help="",
        labels=None,
    ):
        super().__init__(name, help, labels)
        bounds = tuple(float(b) for b in buckets)
        if not bounds or list(bounds) != sorted(set(bounds)) or not all(
            math.isfinite(b) for b in bounds
        ):
            raise ValueError(
                f"Histogram {name!r} buckets must be a non-empty, strictly "
                f"ascending sequence of finite bounds, got {buckets!r}."
            )
        self.buckets = bounds
        self._counts = [0] * len(bounds)  # per-bucket (non-cumulative)
        self._count = 0
        self._sum = 0.0

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = bisect.bisect_left(self.buckets, v)
            if i < len(self._counts):
                self._counts[i] += 1
            self._count += 1
            self._sum += v

    def reset(self) -> None:
        """Zero counts and sum IN PLACE; bounds are immutable."""
        with self._lock:
            self._counts = [0] * len(self.buckets)
            self._count = 0
            self._sum = 0.0

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative_counts(self) -> List[int]:
        """Per-bound cumulative counts (Prometheus ``le`` semantics);
        the implicit ``+Inf`` bucket is :attr:`count`."""
        return self.collect_state()[0]

    def collect_state(self) -> Tuple[List[int], int, float]:
        """``(cumulative_counts, count, sum)`` read under ONE lock
        acquisition: a scrape assembled from separate reads can observe
        ``_count != +Inf bucket`` when a concurrent ``observe`` lands
        between them — spec-invalid exposition text."""
        with self._lock:
            out, total = [], 0
            for c in self._counts:
                total += c
                out.append(total)
            return out, self._count, self._sum


#: The drop-accounting series itself is exempt from the cardinality
#: guard (its variant count is bounded by the number of DISTINCT capped
#: metric names, and capping it would hide the very overflow it
#: reports).
_DROPPED_SERIES = "zk_labels_dropped_total"


class MetricsRegistry:
    """Name table of typed instruments.

    Get-or-create: ``counter/gauge/histogram`` return the existing
    instrument when ``(name, labels)`` was already registered with the
    same type (and, for histograms, the same bounds); a type or bounds
    conflict raises — one name must mean one series shape. A NEW label
    variant past ``max_label_variants`` per name is dropped (detached
    instrument returned; ``zk_labels_dropped_total{metric=}`` bumped,
    warned once per name) — see the module docstring.
    """

    def __init__(self, max_label_variants: int = 64) -> None:
        if max_label_variants < 1:
            raise ValueError(
                f"max_label_variants={max_label_variants} must be >= 1."
            )
        self.max_label_variants = int(max_label_variants)
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, _LabelsKey], _Instrument] = {}
        self._variant_counts: Dict[str, int] = {}
        self._cardinality_warned: Set[str] = set()

    def _get_or_create(self, cls, name, labels, factory):
        key = (str(name), _labels_key(labels))
        warn = False
        with self._lock:
            existing = self._instruments.get(key)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}."
                    )
                return existing
            variants = self._variant_counts.get(key[0], 0)
            if (
                key[0] != _DROPPED_SERIES
                and variants >= self.max_label_variants
            ):
                # Over the cap: fall through to the detached path below
                # (the drop counter is registered OUTSIDE this lock —
                # it goes through _get_or_create itself).
                if key[0] not in self._cardinality_warned:
                    self._cardinality_warned.add(key[0])
                    warn = True
            else:
                inst = factory()
                self._instruments[key] = inst
                self._variant_counts[key[0]] = variants + 1
                return inst
        if warn:
            logger.warning(
                "metric %r is at its label-cardinality cap (%d distinct "
                "label combinations): new variants record into detached "
                "instruments and are NOT exported — an unbounded label "
                "value is feeding this series "
                "(zk_labels_dropped_total{metric=%r} counts the drops)",
                key[0],
                self.max_label_variants,
                key[0],
            )
        self.counter(
            _DROPPED_SERIES,
            help="label variants dropped by the per-metric cardinality "
            "cap",
            labels={"metric": key[0]},
        ).inc()
        # Detached: the caller gets a real, recordable instrument of
        # the right shape; it simply never renders.
        return factory()

    def counter(self, name: str, help: str = "", labels=None) -> Counter:
        return self._get_or_create(
            Counter, name, labels, lambda: Counter(name, help, labels)
        )

    def gauge(
        self, name: str, help: str = "", labels=None, initial: float = 0.0
    ) -> Gauge:
        return self._get_or_create(
            Gauge, name, labels, lambda: Gauge(name, help, labels, initial)
        )

    def histogram(
        self,
        name: str,
        buckets: Sequence[float] = DEFAULT_MS_BUCKETS,
        help: str = "",
        labels=None,
    ) -> Histogram:
        hist = self._get_or_create(
            Histogram,
            name,
            labels,
            lambda: Histogram(name, buckets, help, labels),
        )
        if hist.buckets != tuple(float(b) for b in buckets):
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{hist.buckets}, not {tuple(buckets)!r}."
            )
        return hist

    def collect(self) -> List[_Instrument]:
        """Every registered instrument, registration-ordered (dicts
        preserve insertion order), for exporters."""
        with self._lock:
            return list(self._instruments.values())

    def as_flat_dict(self) -> Dict[str, float]:
        """Scalar view (``/statusz`` + MetricsWriter bridging):
        counters/gauges by name, histograms as ``name_count``/
        ``name_sum``/``name_mean``. Labeled series get a
        ``{k=v,...}`` suffix."""
        out: Dict[str, float] = {}
        for inst in self.collect():
            suffix = (
                "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(inst.labels.items())
                ) + "}"
                if inst.labels
                else ""
            )
            if isinstance(inst, Histogram):
                _, count, total = inst.collect_state()
                out[f"{inst.name}_count{suffix}"] = float(count)
                out[f"{inst.name}_sum{suffix}"] = float(total)
                if count:
                    out[f"{inst.name}_mean{suffix}"] = total / count
            else:
                out[f"{inst.name}{suffix}"] = float(inst.value)
        return out


#: Process-global registry for cross-cutting background subsystems
#: (async-checkpoint queue depth, data prefetch occupancy) that have no
#: natural per-component owner. Component-owned registries (a
#: ``ServingMetrics`` instance's) stay separate so parallel instances
#: never double-count; exporters render both.
_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _DEFAULT
