"""Device memory probe: live per-device HBM gauges.

``jax`` exposes per-device allocator statistics through
``Device.memory_stats()`` (bytes in use, peak bytes, limit) on the TPU
and GPU backends; nothing in the repo surfaced them, so "which process
/ which program owns the HBM" needed a manual profiler capture. The
probe publishes them as registry gauges a live ``/metrics`` scrape
reads:

- ``zk_hbm_bytes_in_use{device=N}`` — current allocator usage.
- ``zk_hbm_peak_bytes_in_use{device=N}`` — the high-water mark (what
  actually bounds batch/bucket sizing).
- ``zk_hbm_bytes_limit{device=N}`` — the per-device capacity.

Backends without allocator stats (CPU returns ``None``) publish the
documented ``-1`` sentinel instead of dropping the series — a
dashboard/CI assertion can always find the gauge, and ``-1 bytes`` is
unambiguous where a silent absence is not (the same convention as
``serving_weights_step``'s bind-time ``-1``).

``poll_once()`` is the deterministic unit (tests/CI); ``start()`` runs
it on a ``zk-device-probe`` daemon thread every ``interval_s``.
Polling reads allocator COUNTERS — no device computation, no sync, no
dispatch — so the probe's cost on the step path is zero by
construction; its host cost is a few microseconds per device per poll
(the bench's ``ZK_BENCH_OBS=1`` leg accounts it as part of the <= 2%
observability budget).
"""

import logging
import threading
from typing import Any, Dict, List, Optional

from zookeeper_tpu.observability.registry import (
    MetricsRegistry,
    default_registry,
)

__all__ = ["DeviceProbe", "device_memory_stats"]

logger = logging.getLogger(__name__)

#: The memory_stats keys published as gauges, in (stats key, gauge
#: suffix) pairs. Backends name them uniformly (PJRT convention).
_STAT_GAUGES = (
    ("bytes_in_use", "zk_hbm_bytes_in_use"),
    ("peak_bytes_in_use", "zk_hbm_peak_bytes_in_use"),
    ("bytes_limit", "zk_hbm_bytes_limit"),
)


def device_memory_stats() -> List[Dict[str, Any]]:
    """Best-effort ``memory_stats()`` for every local device: one dict
    per device (``{"device": i, "kind": ..., **stats}``); ``stats`` is
    empty when the backend exposes none. Never raises — a metrics
    poller must not be able to kill its host process."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return []
    out = []
    for i, dev in enumerate(devices):
        stats: Dict[str, Any] = {}
        try:
            raw = dev.memory_stats()
            if isinstance(raw, dict):
                stats = raw
        except Exception:
            stats = {}
        out.append(
            {
                "device": i,
                "kind": getattr(dev, "device_kind", "unknown"),
                **stats,
            }
        )
    return out


class DeviceProbe:
    """Poll per-device allocator stats into HBM gauges.

    ``registry`` defaults to the process-global one (HBM is a process
    asset with no per-component owner — the same rationale as the
    prefetch-occupancy gauge). Start/stop are idempotent;
    ``poll_once()`` works without a thread (the tier-1/CI mode)."""

    def __init__(
        self,
        interval_s: float = 10.0,
        registry: Optional[MetricsRegistry] = None,
    ) -> None:
        if interval_s <= 0:
            raise ValueError(f"interval_s={interval_s} must be > 0.")
        self._interval_s = float(interval_s)
        self._registry = (
            registry if registry is not None else default_registry()
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> List[Dict[str, Any]]:
        """One poll: publish every device's gauges (``-1`` sentinel
        where the backend exposes no stats) and return the raw stats."""
        stats = device_memory_stats()
        for row in stats:
            labels = {"device": str(row["device"])}
            for stat_key, gauge_name in _STAT_GAUGES:
                value = row.get(stat_key)
                self._registry.gauge(
                    gauge_name,
                    help=f"per-device allocator {stat_key} "
                    "(-1 = backend exposes no memory stats)",
                    labels=labels,
                    initial=-1,
                ).set(float(value) if isinstance(value, (int, float)) else -1)
        return stats

    @property
    def alive(self) -> bool:
        thread = self._thread
        return thread is not None and thread.is_alive()

    def start(self) -> "DeviceProbe":
        if self.alive:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception as e:  # pragma: no cover - defensive
                    logger.warning("device probe poll failed: %s", e)
                self._stop.wait(self._interval_s)

        self._thread = threading.Thread(
            target=loop, name="zk-device-probe", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5)
