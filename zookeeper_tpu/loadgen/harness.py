"""Trace replay against a live serving target (docs/DESIGN.md §24).

``replay(trace, target)`` drives every :class:`TraceRequest` into the
target, classifies each terminal outcome by the exception taxonomy the
serving stack already speaks (``PredictedMissError``/``RejectedError``
⇒ shed, ``DeadlineExpiredError`` ⇒ deadline_expired,
``WorkerCrashedError`` ⇒ crashed, ...), and aggregates an
:class:`SLOReport` — per-phase TTFT/latency percentiles over ADMITTED
requests, goodput tokens/s, outcome counts, retry totals parsed from
the target's ``RequestLog``, and SLO violations (also fired at the
flight recorder so a violating run leaves a debuggable bundle).

Targets, by duck type:

- ``DecodeScheduler`` / ``LMServingConfig`` stack (``submit`` returns a
  stream with ``result()``): open-loop — every request is submitted in
  arrival order FIRST (the queue builds up, which is exactly what
  admission control must see), then resolved.
- ``FleetRouter`` (blocking ``submit`` returning a response object):
  closed-loop over a small thread pool, since each submit blocks for
  its full generation.
- ``MicroBatcher`` (``submit``+``flush``): open-loop; the prompt maps
  to a ``[len(prompt), 1]`` float row block (the batcher serves
  generic row batches, not tokens).
- any callable: ``target(trace_request) -> (tokens, ttft_ms or None)``
  — the escape hatch for custom stacks and harness tests.

An optional ``fault_plan`` is installed for the duration of the replay
(and always cleared), composing any chaos coordinate with the traffic.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from zookeeper_tpu.loadgen.traces import Trace, TraceRequest

__all__ = ["ReplayOutcome", "SLOReport", "replay"]


@dataclasses.dataclass
class ReplayOutcome:
    """One trace request's terminal result."""

    index: int
    rid: Optional[int]
    phase: str
    session: Optional[str]
    outcome: str  # ok | shed | deadline_expired | crashed | unavailable | error
    latency_ms: float
    ttft_ms: Optional[float] = None
    tokens: int = 0
    retried: int = 0
    error: Optional[str] = None


def _percentiles(values: List[float]) -> Dict[str, float]:
    if not values:
        return {}
    arr = np.asarray(values, np.float64)
    return {
        "p50": round(float(np.percentile(arr, 50)), 3),
        "p95": round(float(np.percentile(arr, 95)), 3),
        "p99": round(float(np.percentile(arr, 99)), 3),
    }


@dataclasses.dataclass
class SLOReport:
    """The replay verdict: outcome counts, goodput, per-phase
    percentiles over admitted (ok) requests, violations."""

    trace: str
    seed: int
    wall_s: float
    outcomes: Dict[str, int]
    per_phase: Dict[str, Dict[str, Any]]
    goodput_tokens_per_sec: float
    ok_tokens: int
    retried_total: int
    violations: List[Dict[str, Any]]
    results: List[ReplayOutcome] = dataclasses.field(repr=False)

    @property
    def total(self) -> int:
        return sum(self.outcomes.values())

    def as_dict(self) -> Dict[str, Any]:
        """JSON-ready summary (everything but the raw per-request
        list)."""
        return {
            "trace": self.trace,
            "seed": self.seed,
            "wall_s": round(self.wall_s, 3),
            "requests": self.total,
            "outcomes": dict(self.outcomes),
            "per_phase": self.per_phase,
            "goodput_tokens_per_sec": round(
                self.goodput_tokens_per_sec, 3
            ),
            "ok_tokens": self.ok_tokens,
            "retried_total": self.retried_total,
            "violations": len(self.violations),
        }


# -- outcome classification ----------------------------------------------


def _classify(error: Optional[BaseException]) -> str:
    from zookeeper_tpu.serving.batcher import (
        DeadlineExpiredError,
        RejectedError,
        WorkerCrashedError,
    )
    from zookeeper_tpu.serving.fleet import FleetUnavailableError

    if error is None:
        return "ok"
    if isinstance(error, RejectedError):  # PredictedMissError included
        return "shed"
    if isinstance(error, DeadlineExpiredError):
        return "deadline_expired"
    if isinstance(error, WorkerCrashedError):
        return "crashed"
    if isinstance(error, FleetUnavailableError):
        return "unavailable"
    return "error"


def _retried_from_log(target: Any, rid: Optional[int]) -> int:
    """``retried=N`` parsed out of the target RequestLog's detail
    field — the rid-preserving retry counter the router records."""
    log = getattr(target, "request_log", None)
    if log is None or rid is None:
        return 0
    find = getattr(log, "find", None)
    rec = find(rid) if find is not None else None
    detail = (rec or {}).get("detail") or ""
    for part in str(detail).split():
        if part.startswith("retried="):
            try:
                return int(part.split("=", 1)[1])
            except ValueError:
                return 0
    return 0


# -- target adapters -----------------------------------------------------


def _is_router(target: Any) -> bool:
    return hasattr(target, "replicas") and hasattr(target, "submit")


def _is_stream_scheduler(target: Any) -> bool:
    return hasattr(target, "submit") and hasattr(target, "drain")


def _is_batcher(target: Any) -> bool:
    return hasattr(target, "submit") and hasattr(target, "flush")


def _open_loop_submit(
    target: Any, req: TraceRequest
) -> Tuple[Optional[int], Callable[[], Tuple[int, Optional[float]]]]:
    """Enqueue one request on a non-blocking target; returns ``(rid,
    resolve)`` where ``resolve()`` blocks for ``(tokens, ttft_ms)``."""
    if _is_stream_scheduler(target):
        stream = target.submit(
            np.asarray(req.prompt, np.int32),
            max_new_tokens=req.max_new_tokens,
            deadline_ms=req.deadline_ms,
        )
        return stream.rid, lambda: (
            int(stream.result().shape[0]),
            stream.ttft_ms,
        )
    if _is_batcher(target):
        pending = target.submit(
            np.asarray(req.prompt, np.float32)[:, None],
            deadline_ms=req.deadline_ms,
        )
        return pending.rid, lambda: (
            int(np.asarray(pending.result()).shape[0]),
            None,
        )
    raise TypeError(
        f"cannot open-loop replay against {type(target).__name__}: "
        "expected a stream scheduler (submit+drain), a batcher "
        "(submit+flush), a FleetRouter, or a callable."
    )


# -- the replay ----------------------------------------------------------


def replay(
    trace: Trace,
    target: Any,
    *,
    fault_plan: Any = None,
    mode: str = "auto",
    concurrency: int = 8,
    time_scale: float = 0.0,
    slo_ttft_ms: Optional[float] = None,
    slo_latency_ms: Optional[float] = None,
) -> SLOReport:
    """Replay ``trace`` against ``target`` and report.

    ``time_scale`` maps trace arrival offsets onto real time: 1.0
    replays at recorded speed, 0.0 (the deterministic default) submits
    as fast as the target admits — arrival ORDER is what matters to
    admission control, and the queue the open-loop burst builds is the
    overload under test. ``mode`` is ``auto`` (sniff the target),
    ``open_loop`` (submit everything, then resolve) or ``threaded``
    (closed-loop pool for blocking targets). ``fault_plan`` installs a
    chaos plan for the duration of the replay. SLO thresholds, when
    given, turn slow ADMITTED requests into violations (each also
    fired at the flight recorder, so a violating run leaves a
    bundle)."""
    from zookeeper_tpu.observability import recorder as _recorder
    from zookeeper_tpu.resilience import faults

    # Pre-warm the classification imports BEFORE the clock starts —
    # the first _classify call would otherwise charge the serving
    # import chain to one request's measured latency.
    _classify(None)

    if mode == "auto":
        mode = "threaded" if _is_router(target) or callable(target) else (
            "open_loop"
        )
    if mode not in ("open_loop", "threaded"):
        raise ValueError(
            f"mode={mode!r} unknown; choose auto/open_loop/threaded."
        )
    if concurrency < 1:
        raise ValueError(f"concurrency={concurrency} must be >= 1.")

    results: List[Optional[ReplayOutcome]] = [None] * len(trace.requests)
    if fault_plan is not None:
        faults.install(fault_plan)
    t_start = time.perf_counter()
    try:
        if mode == "open_loop":
            _replay_open_loop(trace, target, results, time_scale, t_start)
        else:
            _replay_threaded(
                trace, target, results, time_scale, t_start, concurrency
            )
    finally:
        if fault_plan is not None:
            faults.clear()
    wall_s = max(time.perf_counter() - t_start, 1e-9)

    # Retries come from the target's RequestLog detail, not the
    # exception path — a retried-then-ok request raises nothing.
    for out in results:
        if out is not None and out.retried == 0:
            out.retried = _retried_from_log(target, out.rid)

    outcomes: Dict[str, int] = {}
    ok_tokens = 0
    retried_total = 0
    violations: List[Dict[str, Any]] = []
    per_phase: Dict[str, Dict[str, Any]] = {}
    final = [o for o in results if o is not None]
    for out in final:
        outcomes[out.outcome] = outcomes.get(out.outcome, 0) + 1
        retried_total += out.retried
        if out.outcome == "ok":
            ok_tokens += out.tokens
            breached = []
            if (
                slo_ttft_ms is not None
                and out.ttft_ms is not None
                and out.ttft_ms > slo_ttft_ms
            ):
                breached.append(f"ttft_ms={out.ttft_ms:.1f}")
            if (
                slo_latency_ms is not None
                and out.latency_ms > slo_latency_ms
            ):
                breached.append(f"latency_ms={out.latency_ms:.1f}")
            if breached:
                v = {
                    "index": out.index,
                    "rid": out.rid,
                    "phase": out.phase,
                    "breached": breached,
                }
                violations.append(v)
                _recorder.notify("slo_violation", attrs=v)
    for phase in trace.phases():
        ph = [o for o in final if o.phase == phase]
        ok = [o for o in ph if o.outcome == "ok"]
        per_phase[phase] = {
            "requests": len(ph),
            "ok": len(ok),
            "latency_ms": _percentiles([o.latency_ms for o in ok]),
            "ttft_ms": _percentiles(
                [o.ttft_ms for o in ok if o.ttft_ms is not None]
            ),
            # Per-request mean inter-token gap ((latency - TTFT) /
            # (tokens - 1)) — the interference tail chunked prefill
            # exists to flatten (docs/DESIGN.md §25). Single-token
            # streams have no gap and are excluded.
            "itl_ms": _percentiles([
                (o.latency_ms - o.ttft_ms) / (o.tokens - 1)
                for o in ok
                if o.ttft_ms is not None and o.tokens > 1
            ]),
        }
    return SLOReport(
        trace=trace.name,
        seed=trace.seed,
        wall_s=wall_s,
        outcomes=outcomes,
        per_phase=per_phase,
        goodput_tokens_per_sec=ok_tokens / wall_s,
        ok_tokens=ok_tokens,
        retried_total=retried_total,
        violations=violations,
        results=final,
    )


def _pace(req: TraceRequest, time_scale: float, t_start: float) -> None:
    if time_scale <= 0:
        return
    due = t_start + req.at_ms * time_scale / 1e3
    delay = due - time.perf_counter()
    if delay > 0:
        time.sleep(delay)


def _replay_open_loop(
    trace: Trace,
    target: Any,
    results: List[Optional[ReplayOutcome]],
    time_scale: float,
    t_start: float,
) -> None:
    """Submit every request in arrival order (building the queue the
    admission control sees), then resolve in order."""
    handles: List[Tuple[int, Optional[int], float, Any]] = []
    for i, req in enumerate(trace.requests):
        _pace(req, time_scale, t_start)
        t0 = time.perf_counter()
        try:
            rid, resolve = _open_loop_submit(target, req)
        except BaseException as e:  # admission-time terminal outcome
            results[i] = ReplayOutcome(
                index=req.index,
                rid=None,
                phase=req.phase,
                session=req.session,
                outcome=_classify(e),
                latency_ms=(time.perf_counter() - t0) * 1e3,
                error=type(e).__name__,
            )
            continue
        handles.append((i, rid, t0, resolve))
    for i, rid, t0, resolve in handles:
        req = trace.requests[i]
        error: Optional[BaseException] = None
        tokens, ttft = 0, None
        try:
            tokens, ttft = resolve()
        except BaseException as e:
            error = e
        results[i] = ReplayOutcome(
            index=req.index,
            rid=rid,
            phase=req.phase,
            session=req.session,
            outcome=_classify(error),
            latency_ms=(time.perf_counter() - t0) * 1e3,
            ttft_ms=ttft,
            tokens=tokens,
            error=type(error).__name__ if error is not None else None,
        )


def _replay_threaded(
    trace: Trace,
    target: Any,
    results: List[Optional[ReplayOutcome]],
    time_scale: float,
    t_start: float,
    concurrency: int,
) -> None:
    """Closed-loop replay for BLOCKING targets (FleetRouter, callables):
    a small pool pulls requests in arrival order; each worker blocks
    for its request's full generation."""
    lock = threading.Lock()
    cursor = [0]

    def submit_one(req: TraceRequest) -> ReplayOutcome:
        _pace(req, time_scale, t_start)
        t0 = time.perf_counter()
        error: Optional[BaseException] = None
        rid, tokens, ttft, retried = None, 0, None, 0
        try:
            if callable(target) and not _is_router(target):
                tokens, ttft = target(req)
                tokens = int(tokens)
            else:
                resp = target.submit(
                    np.asarray(req.prompt, np.int32),
                    session=req.session,
                    max_new_tokens=req.max_new_tokens,
                )
                rid = resp.rid
                tokens = int(np.asarray(resp.tokens).shape[0])
                ttft = resp.ttft_ms
        except BaseException as e:
            error = e
        return ReplayOutcome(
            index=req.index,
            rid=rid,
            phase=req.phase,
            session=req.session,
            outcome=_classify(error),
            latency_ms=(time.perf_counter() - t0) * 1e3,
            ttft_ms=ttft,
            tokens=tokens,
            retried=retried,
            error=type(error).__name__ if error is not None else None,
        )

    def worker() -> None:
        while True:
            with lock:
                i = cursor[0]
                if i >= len(trace.requests):
                    return
                cursor[0] = i + 1
            results[i] = submit_one(trace.requests[i])

    threads = [
        threading.Thread(target=worker, name=f"loadgen-{k}", daemon=True)
        for k in range(min(concurrency, max(1, len(trace.requests))))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
