"""Deterministic arrival-trace generation (docs/DESIGN.md §24).

A trace is a list of :class:`TraceRequest` — arrival offset, prompt
tokens, generation budget, deadline, optional session — plus the seed
that produced it. Everything is sampled through ``AugRng(seed,
request_index, FIELD_STREAM)``: one independent splitmix64 stream per
(request, field), so inserting a generator knob never perturbs the
draws of unrelated fields, and the same seed reproduces the same trace
byte-for-byte on any host. No wall-clock reads happen anywhere in this
module — arrivals are OFFSETS (ms from trace start) that the harness
maps onto real time at replay.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any, Dict, Iterable, List, Optional, Sequence

from zookeeper_tpu.data.augrng import AugRng

__all__ = [
    "Trace",
    "TraceRequest",
    "diurnal_ramp",
    "from_request_log",
    "poisson_burst",
    "session_mix",
]

# Per-field stream ids (the AugRng ``epoch`` coordinate): each sampled
# quantity draws from its own counter stream keyed on the REQUEST
# index, so field draws never interleave.
_S_ARRIVAL = 0
_S_PROMPT_LEN = 1
_S_OUT_LEN = 2
_S_TOKENS = 3
_S_SESSION = 4


@dataclasses.dataclass
class TraceRequest:
    """One request in a trace: WHEN it arrives (ms offset from trace
    start), WHAT it asks (prompt tokens + generation budget +
    deadline), and WHO it is (optional multi-turn session key)."""

    index: int
    at_ms: float
    prompt: List[int]
    max_new_tokens: int = 16
    deadline_ms: Optional[float] = None
    session: Optional[str] = None
    #: Generator-assigned phase label ("base"/"burst"/"cooldown"/...)
    #: the SLO report aggregates per-phase percentiles under.
    phase: str = "base"


@dataclasses.dataclass
class Trace:
    """A named, seed-keyed request schedule. ``requests`` is sorted by
    ``at_ms`` (generators guarantee it; ``load`` re-sorts)."""

    name: str
    seed: int
    requests: List[TraceRequest]

    @property
    def duration_ms(self) -> float:
        return self.requests[-1].at_ms if self.requests else 0.0

    def phases(self) -> List[str]:
        """Phase labels in first-appearance order."""
        seen: List[str] = []
        for r in self.requests:
            if r.phase not in seen:
                seen.append(r.phase)
        return seen

    def stats(self) -> Dict[str, Any]:
        """Workload-shape summary (also the bench's informational
        keys): count, duration, mean prompt/output lengths, sessions."""
        n = len(self.requests)
        if n == 0:
            return {"requests": 0}
        return {
            "requests": n,
            "duration_ms": round(self.duration_ms, 3),
            "mean_prompt_tokens": round(
                sum(len(r.prompt) for r in self.requests) / n, 2
            ),
            "max_prompt_tokens": max(len(r.prompt) for r in self.requests),
            "mean_new_tokens": round(
                sum(r.max_new_tokens for r in self.requests) / n, 2
            ),
            "sessions": len(
                {r.session for r in self.requests if r.session is not None}
            ),
            "phases": {
                p: sum(1 for r in self.requests if r.phase == p)
                for p in self.phases()
            },
        }

    # -- (de)serialization -----------------------------------------------

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(
                {
                    "name": self.name,
                    "seed": self.seed,
                    "requests": [
                        dataclasses.asdict(r) for r in self.requests
                    ],
                },
                f,
            )

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path) as f:
            raw = json.load(f)
        reqs = [TraceRequest(**r) for r in raw["requests"]]
        reqs.sort(key=lambda r: (r.at_ms, r.index))
        return cls(
            name=str(raw["name"]), seed=int(raw["seed"]), requests=reqs
        )


# -- sampling primitives -------------------------------------------------


def _exp_gap_ms(rng: AugRng, rate_rps: float) -> float:
    """One exponential inter-arrival gap for a Poisson process at
    ``rate_rps``. ``-log(1-u)`` with u in [0,1) never takes log(0)."""
    u = rng.uniform(0.0, 1.0)
    return -math.log(1.0 - u) / rate_rps * 1e3


def _pareto_int(rng: AugRng, lo: int, hi: int, alpha: float) -> int:
    """Bounded-Pareto integer in [lo, hi]: inverse-transform
    ``lo / u**(1/alpha)`` clamped at ``hi`` — the heavy tail real
    prompt/output length distributions show (most short, a few huge)."""
    u = rng.uniform(0.0, 1.0)
    u = max(u, 1e-12)  # u=0 would be an infinite draw
    return min(hi, max(lo, int(lo / u ** (1.0 / alpha))))


def _prompt(rng: AugRng, length: int, vocab: int) -> List[int]:
    """Tokens in [1, vocab): 0 is reserved (pad/eos in the tiny serving
    configs), so a generated prompt can never fake an EOS."""
    return [1 + rng.randint(vocab - 1) for _ in range(length)]


def _fill(
    reqs: List[TraceRequest],
    seed: int,
    *,
    vocab: int,
    prompt_lo: int,
    prompt_hi: int,
    out_lo: int,
    out_hi: int,
    alpha: float,
    deadline_ms: Optional[float],
) -> None:
    """Sample prompt/output sizes + tokens for requests that carry only
    arrival metadata. Heavy-tailed in BOTH dimensions."""
    for r in reqs:
        plen = _pareto_int(
            AugRng(seed, r.index, _S_PROMPT_LEN), prompt_lo, prompt_hi, alpha
        )
        r.prompt = _prompt(AugRng(seed, r.index, _S_TOKENS), plen, vocab)
        r.max_new_tokens = _pareto_int(
            AugRng(seed, r.index, _S_OUT_LEN), out_lo, out_hi, alpha
        )
        r.deadline_ms = deadline_ms


# -- generators ----------------------------------------------------------


def poisson_burst(
    seed: int,
    *,
    base_rate_rps: float = 20.0,
    burst_rate_rps: float = 200.0,
    base_s: float = 1.0,
    burst_s: float = 1.0,
    cooldown_s: float = 1.0,
    vocab: int = 64,
    prompt_len: int = 4,
    max_prompt_len: int = 24,
    new_tokens: int = 4,
    max_new_tokens: int = 16,
    tail_alpha: float = 1.5,
    deadline_ms: Optional[float] = None,
    name: str = "poisson_burst",
) -> Trace:
    """Piecewise-constant-rate Poisson arrivals: a ``base`` phase, a
    ``burst`` phase at ``burst_rate_rps`` (the overload the guardrails
    exist for), and a ``cooldown`` phase back at base rate (where the
    system should RECOVER — brown-out release, breaker close). Prompt
    and output lengths are bounded-Pareto heavy-tailed."""
    if base_rate_rps <= 0 or burst_rate_rps <= 0:
        raise ValueError("arrival rates must be > 0 rps.")
    phases = [
        ("base", base_s, base_rate_rps),
        ("burst", burst_s, burst_rate_rps),
        ("cooldown", cooldown_s, base_rate_rps),
    ]
    reqs: List[TraceRequest] = []
    t_ms, index = 0.0, 0
    for phase, dur_s, rate in phases:
        end_ms = t_ms + dur_s * 1e3
        while True:
            t_ms += _exp_gap_ms(AugRng(seed, index, _S_ARRIVAL), rate)
            if t_ms >= end_ms:
                t_ms = end_ms
                break
            reqs.append(
                TraceRequest(index=index, at_ms=t_ms, prompt=[], phase=phase)
            )
            index += 1
    _fill(
        reqs,
        seed,
        vocab=vocab,
        prompt_lo=prompt_len,
        prompt_hi=max_prompt_len,
        out_lo=new_tokens,
        out_hi=max_new_tokens,
        alpha=tail_alpha,
        deadline_ms=deadline_ms,
    )
    return Trace(name=name, seed=seed, requests=reqs)


def diurnal_ramp(
    seed: int,
    *,
    peak_rate_rps: float = 100.0,
    trough_frac: float = 0.1,
    duration_s: float = 4.0,
    cycles: float = 1.0,
    vocab: int = 64,
    prompt_len: int = 4,
    max_prompt_len: int = 24,
    new_tokens: int = 4,
    max_new_tokens: int = 16,
    tail_alpha: float = 1.5,
    deadline_ms: Optional[float] = None,
    name: str = "diurnal_ramp",
) -> Trace:
    """Sinusoidal-rate arrivals via thinning: candidates are drawn at
    the peak rate and kept with probability ``rate(t)/peak`` — the
    standard non-homogeneous Poisson construction, exact and purely
    counter-keyed. Phases label the half-cycles (``ramp_up``/
    ``ramp_down``) so the report shows how the system tracks a moving
    operating point rather than a step."""
    if peak_rate_rps <= 0 or not (0.0 <= trough_frac <= 1.0):
        raise ValueError(
            "peak_rate_rps must be > 0 and trough_frac in [0, 1]."
        )
    end_ms = duration_s * 1e3
    omega = 2.0 * math.pi * cycles / end_ms
    reqs: List[TraceRequest] = []
    t_ms, index, candidate = 0.0, 0, 0
    while True:
        rng = AugRng(seed, candidate, _S_ARRIVAL)
        t_ms += _exp_gap_ms(rng, peak_rate_rps)
        candidate += 1
        if t_ms >= end_ms:
            break
        # rate(t)/peak: trough..1.0 sinusoid starting at the trough.
        level = trough_frac + (1.0 - trough_frac) * 0.5 * (
            1.0 - math.cos(omega * t_ms)
        )
        if rng.uniform(0.0, 1.0) >= level:
            continue  # thinned
        rising = math.sin(omega * t_ms) >= 0.0
        reqs.append(
            TraceRequest(
                index=index,
                at_ms=t_ms,
                prompt=[],
                phase="ramp_up" if rising else "ramp_down",
            )
        )
        index += 1
    _fill(
        reqs,
        seed,
        vocab=vocab,
        prompt_lo=prompt_len,
        prompt_hi=max_prompt_len,
        out_lo=new_tokens,
        out_hi=max_new_tokens,
        alpha=tail_alpha,
        deadline_ms=deadline_ms,
    )
    return Trace(name=name, seed=seed, requests=reqs)


def session_mix(
    seed: int,
    *,
    sessions: int = 8,
    turns: int = 4,
    rate_rps: float = 50.0,
    shared_prefix_len: int = 8,
    turn_tokens: int = 4,
    vocab: int = 64,
    new_tokens: int = 4,
    max_new_tokens: int = 16,
    tail_alpha: float = 1.5,
    deadline_ms: Optional[float] = None,
    name: str = "session_mix",
) -> Trace:
    """Multi-turn conversations over a COMMON system prefix: every
    session's turn-k prompt is ``shared_prefix + session_tokens[: k *
    turn_tokens]`` — the growing-prefix shape that exercises the radix
    cache (turn k re-enters turn k-1's pages) and the router's session
    pinning. Turns arrive round-robin across sessions on one Poisson
    clock, so sessions INTERLEAVE (the cache-thrash case, not the
    one-conversation-at-a-time one)."""
    if sessions < 1 or turns < 1:
        raise ValueError("sessions and turns must be >= 1.")
    shared = _prompt(
        AugRng(seed, 0, _S_SESSION), shared_prefix_len, vocab
    )
    # Each session's private token tail, drawn once up front; turn k
    # exposes a prefix of it — strictly growing, never rewritten.
    tails = [
        _prompt(
            AugRng(seed, 1 + s, _S_SESSION), turns * turn_tokens, vocab
        )
        for s in range(sessions)
    ]
    reqs: List[TraceRequest] = []
    t_ms, index = 0.0, 0
    for turn in range(turns):
        for s in range(sessions):
            t_ms += _exp_gap_ms(AugRng(seed, index, _S_ARRIVAL), rate_rps)
            reqs.append(
                TraceRequest(
                    index=index,
                    at_ms=t_ms,
                    prompt=shared + tails[s][: (turn + 1) * turn_tokens],
                    max_new_tokens=_pareto_int(
                        AugRng(seed, index, _S_OUT_LEN),
                        new_tokens,
                        max_new_tokens,
                        tail_alpha,
                    ),
                    deadline_ms=deadline_ms,
                    session=f"s{s}",
                    phase=f"turn{turn}",
                )
            )
            index += 1
    return Trace(name=name, seed=seed, requests=reqs)


def from_request_log(
    records: Iterable[Dict[str, Any]],
    *,
    seed: int,
    vocab: int = 64,
    default_new_tokens: int = 8,
    deadline_ms: Optional[float] = None,
    name: str = "replayed_log",
) -> Trace:
    """Rebuild a replayable trace from recorded ``RequestLog`` entries
    (``tail()`` dicts or a flight-recorder bundle's requests section):
    arrivals come from ``enqueue_ns`` offsets, generation budgets from
    the recorded ``tokens`` count, prompt SIZES from ``rows`` when
    present. Token CONTENT is not recorded, so prompts are synthesized
    from ``seed`` — the replay reproduces the log's arrival process and
    size mix, not its exact text."""
    recs = [r for r in records if r.get("enqueue_ns") is not None]
    recs.sort(key=lambda r: r["enqueue_ns"])
    if not recs:
        return Trace(name=name, seed=seed, requests=[])
    t0 = recs[0]["enqueue_ns"]
    reqs: List[TraceRequest] = []
    for i, rec in enumerate(recs):
        plen = int(rec.get("rows") or 0)
        if plen < 1:
            plen = _pareto_int(AugRng(seed, i, _S_PROMPT_LEN), 2, 16, 1.5)
        reqs.append(
            TraceRequest(
                index=i,
                at_ms=(rec["enqueue_ns"] - t0) / 1e6,
                prompt=_prompt(AugRng(seed, i, _S_TOKENS), plen, vocab),
                max_new_tokens=int(
                    rec.get("tokens") or default_new_tokens
                ),
                deadline_ms=deadline_ms,
                phase="replay",
            )
        )
    return Trace(name=name, seed=seed, requests=reqs)
