"""Trace-driven load generation + replay harness (docs/DESIGN.md §24).

The scenario-diversity engine the overload guardrails are judged
against: ``traces.py`` generates deterministic seed-keyed arrival
traces (Poisson bursts, diurnal ramps, heavy-tailed prompt/output
lengths, shared-prefix multi-turn session mixes) and converts recorded
``RequestLog`` JSON back into replayable traces; ``harness.py`` replays
a trace against any serving target (a :class:`MicroBatcher` stack, an
``LMServingConfig`` decode scheduler, a :class:`FleetRouter` over real
worker processes), optionally composed with a ``FaultPlan`` chaos leg,
and emits a structured :class:`SLOReport` — per-phase latency/TTFT
percentiles, goodput, terminal outcome counts, SLO violations.

Determinism contract: every sampled quantity in a trace derives from
``AugRng(seed, request_index, FIELD_STREAM)`` — the splitmix64 counter
discipline the data pipeline uses. No wall-clock reads happen during
generation; two calls with the same seed produce byte-identical traces
on any host.
"""

from zookeeper_tpu.loadgen.harness import (
    ReplayOutcome,
    SLOReport,
    replay,
)
from zookeeper_tpu.loadgen.traces import (
    Trace,
    TraceRequest,
    diurnal_ramp,
    from_request_log,
    poisson_burst,
    session_mix,
)

__all__ = [
    "ReplayOutcome",
    "SLOReport",
    "Trace",
    "TraceRequest",
    "diurnal_ramp",
    "from_request_log",
    "poisson_burst",
    "replay",
    "session_mix",
]
