"""Resilience subsystem: failure is the common case.

A production-scale system (ROADMAP north star) runs on preemptible
pools, flaky disks, and unattended numerics; this package makes every
one of those a *recoverable, tested* event instead of a lost run:

- :class:`PreemptionGuard` (``guard.py``): SIGTERM/SIGINT become a flag
  checked at step/slab boundaries; the training loop saves once,
  synchronously, and exits with the distinguished :class:`Preempted`
  status.
- :func:`run_with_recovery` (``supervisor.py``): budgeted, backoff'd
  restarts of an experiment; resumed runs restore from the checkpointer
  and replay the ``(seed, epoch)``-deterministic pipeline for EXACT
  mid-epoch resume. :class:`RecoveryResult` reports restarts and
  restore latency.
- :class:`FaultPlan` (``faults.py``): deterministic, process-local
  fault injection (kill at step N, corrupt a checkpoint, fail a save,
  NaN a step, crash the serving worker, kill/ tear a specific HOST of
  a process group) driving the chaos test suite — every recovery leg
  is walked bit-exactly in tier-1, not just claimed.
- :class:`FileCoordinator` (``coordination.py``): the shared-directory
  flag/exchange primitive the multi-host protocols ride — group
  preemption drains, supervisor restart verdicts, and the per-host
  checkpoint restore agreement (docs/DESIGN.md §19).

Crash-consistent restore (fallback to the newest VALID retained step)
and retrying saves live in ``training.checkpoint.Checkpointer``;
non-finite-loss policies in ``training.step.make_train_step``
(``nan_policy``); serving deadlines / load-shedding / worker-restart in
``serving.batcher.MicroBatcher``. docs/DESIGN.md §10 is the failure
model tying them together.
"""

from zookeeper_tpu.resilience.coordination import (
    CoordinatorLostError,
    FileCoordinator,
    HostCoordinator,
    NullCoordinator,
)
from zookeeper_tpu.resilience.faults import (
    FaultPlan,
    InjectedFault,
    NonFiniteLossError,
    Preempted,
    corrupt_checkpoint_dir,
)
from zookeeper_tpu.resilience.guard import PreemptionGuard
from zookeeper_tpu.resilience.supervisor import (
    RECOVERABLE,
    GroupPeerFailure,
    RecoveryResult,
    measure_recovery_restore_ms,
    run_with_recovery,
)

__all__ = [
    "CoordinatorLostError",
    "FaultPlan",
    "FileCoordinator",
    "GroupPeerFailure",
    "HostCoordinator",
    "InjectedFault",
    "NonFiniteLossError",
    "NullCoordinator",
    "Preempted",
    "PreemptionGuard",
    "RECOVERABLE",
    "RecoveryResult",
    "corrupt_checkpoint_dir",
    "measure_recovery_restore_ms",
    "run_with_recovery",
]
