"""Preemption safety: turn SIGTERM/SIGINT into a clean boundary exit.

TPU pools preempt: the scheduler sends SIGTERM and the process has a
grace window. Without a guard that kills training wherever the Python
loop happens to be — up to ``save_every_steps`` of work lost and a
possibly-torn async save on disk. The :class:`PreemptionGuard` installs
signal handlers that only *set a flag*; the training loop checks the
flag at safe boundaries (step/slab ends, where the state is a valid
exact-resume point), performs ONE synchronous checkpoint save, and
raises :class:`~zookeeper_tpu.resilience.faults.Preempted` — the
distinguished status a supervisor (``run_with_recovery``) resumes from.

The guard never acts from inside the signal handler (async-signal
safety: a handler that checkpoints could re-enter orbax mid-save);
everything happens on the training thread at the next boundary check.
Fault injection reuses the same flag: ``FaultPlan(kill_at_step=N)``
calls :meth:`request_preemption` at the boundary, so the injected-kill
path and the real-SIGTERM path are one code path.
"""

import signal
import threading
from typing import Any, Optional, Sequence, Tuple

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.observability import trace as _trace


@component
class PreemptionGuard:
    """Boundary-checked preemption flag with scoped signal handlers.

    ``install()``/``uninstall()`` bracket a training run (the experiment
    does this); while installed, SIGTERM/SIGINT set the flag instead of
    killing the process, and the previous handlers are restored on
    uninstall — a second signal after uninstall behaves exactly as it
    would have without the guard. Installation is skipped quietly off
    the main thread (CPython restricts ``signal.signal`` to it);
    :meth:`request_preemption` still works there, so fault-injected and
    programmatic preemption stay testable anywhere.
    """

    enabled: bool = Field(True)
    #: Catch SIGINT too (Ctrl-C becomes a clean save-and-exit). Set
    #: False to keep KeyboardInterrupt's immediate-abort behavior.
    handle_sigint: bool = Field(True)

    def _state(self) -> dict:
        st = getattr(self, "_guard_state", None)
        if st is None:
            st = {
                "flag": threading.Event(),
                "prev": {},
                "installed": False,
                "signal": None,
                "origin": None,
            }
            object.__setattr__(self, "_guard_state", st)
        return st

    @property
    def preempted(self) -> bool:
        return self._state()["flag"].is_set()

    @property
    def received_signal(self) -> Optional[int]:
        """The signal number that tripped the flag (None for
        programmatic/injected preemption)."""
        return self._state()["signal"]

    @property
    def preemption_origin(self) -> Optional[int]:
        """The PROCESS INDEX whose signal/fault originated a group
        preemption (None for a local/single-process one) — carried into
        the group supervisor's flight-recorder manifest so a pod-wide
        drain names the host that started it."""
        return self._state()["origin"]

    def request_preemption(
        self, signum: Optional[int] = None, origin: Optional[int] = None
    ) -> None:
        """Trip the flag programmatically (fault injection, tests, an
        external watcher thread polling a cloud preemption notice, or
        the group-boundary exchange relaying a PEER host's preemption —
        ``origin`` then names that host)."""
        st = self._state()
        st["signal"] = signum
        if origin is not None:
            st["origin"] = int(origin)
        st["flag"].set()
        # Async-signal-safe enough: one deque append, no locks taken.
        _trace.event(
            "preemption_requested", attrs={"signal": signum, "origin": origin}
        )

    def _signals(self) -> Sequence[int]:
        sigs = [signal.SIGTERM]
        if self.handle_sigint:
            sigs.append(signal.SIGINT)
        return sigs

    def install(self) -> "PreemptionGuard":
        """Install the handlers (idempotent). Clears a stale flag from a
        previous run so a resumed experiment doesn't instantly re-exit."""
        st = self._state()
        st["flag"].clear()
        st["signal"] = None
        st["origin"] = None
        if not self.enabled or st["installed"]:
            return self

        def handler(signum, frame):
            # Flag only — NEVER checkpoint from a signal handler.
            self.request_preemption(signum)

        try:
            for sig in self._signals():
                st["prev"][sig] = signal.signal(sig, handler)
            st["installed"] = True
        except ValueError:
            # Not the main thread: signals can't be hooked here, but
            # request_preemption() remains fully functional.
            st["prev"].clear()
        return self

    def preemption_save(
        self, checkpointer: Any, state: Any, global_step: int
    ) -> Tuple[bool, float]:
        """The ONE preemption-boundary save policy, shared by every
        loop shape and both checkpoint modes (docs/DESIGN.md §10/§12):

        1. Drain the async writer — the process is about to die, so any
           queued/in-flight background write must land first. Under
           ``queue_policy="supersede"`` the queued-but-not-started
           snapshot is dropped instead (the final save below writes a
           strictly newer state); the in-flight write always completes.
        2. ONE SYNCHRONOUS save of exactly this boundary state (skipped
           when a cadence save already landed on this step, or when
           best-ranking retention makes a metric-less save unrankable —
           the latest ranked save is then the resume point).
        3. ``wait()`` so the bytes are durable before the grace window
           closes.

        Returns ``(saved, save_wait_ms)`` — the wait is the time spent
        in step 1, the async-mode addition to the preemption budget
        that ``run_with_recovery`` surfaces per attempt. SIGTERM
        semantics are therefore UNCHANGED by async mode: the process
        still exits having synchronously saved the newest state.
        """
        saved = False
        wait_ms = 0.0
        if checkpointer.enabled:
            # Superseding the queued snapshot is only sound when the
            # final save below actually replaces it with newer state;
            # under best-ranking retention the final save is SKIPPED
            # (metric-less saves are unrankable), so a queued ranked
            # snapshot must be written out, not dropped.
            supersede = (
                checkpointer.queue_policy == "supersede"
                and checkpointer.keep_best_metric is None
            )
            with _trace.span("preemption_drain", step=global_step):
                wait_ms = checkpointer.drain_async(supersede=supersede)
            if checkpointer.keep_best_metric is not None:
                # Rank-managed retention can't accept a metric-less
                # save; the latest ranked save is the resume point.
                saved = checkpointer.latest_step() is not None
            elif checkpointer.latest_step() == global_step:
                saved = True  # a cadence save just landed on this step
            else:
                with _trace.span("preemption_save", step=global_step):
                    saved = bool(checkpointer.save(state, sync=True))
            checkpointer.wait()  # synchronous: the process may die next
        return saved, wait_ms

    def uninstall(self) -> "PreemptionGuard":
        """Restore the pre-install handlers (idempotent)."""
        st = self._state()
        if st["installed"]:
            for sig, prev in st["prev"].items():
                try:
                    signal.signal(sig, prev)
                except (ValueError, TypeError):
                    pass
            st["prev"].clear()
            st["installed"] = False
        return self
