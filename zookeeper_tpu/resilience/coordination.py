"""Cross-host coordination for the multi-process resilience stack.

The group-recovery and per-host-checkpoint protocols (docs/DESIGN.md
§19) need two tiny primitives that work BETWEEN processes of one
training job, without assuming the jax collective runtime is healthy
(it is exactly the thing that may be mid-failure):

- **flag publish/poll** — a non-blocking "host i wants to stop" signal
  every host can see at its next step/slab boundary (the coordinated
  preemption drain), and
- **exchange** — a small-value allgather with a deadline (restore-step
  agreement, supervisor restart verdicts, stop-step rendezvous).

:class:`FileCoordinator` implements both over a SHARED DIRECTORY
(the same shared storage the checkpoint root already lives on for the
per-host commit protocol): every write is temp-file → atomic rename,
every read tolerates missing/partial peers, and every round is
namespaced by ``(generation, key, sequence)`` so a restarted group
attempt can never consume a previous attempt's stale files. No
collectives, no sockets, no extra deps — a host that died simply never
produces its file and the peers time out with
:class:`CoordinatorLostError` instead of hanging in a collective.

:class:`NullCoordinator` is the single-process no-op every API
degrades to, so wiring a coordinator unconditionally costs nothing at
``process_count == 1``.

Determinism contract: both primitives carry only small JSON payloads
keyed by LOGICAL coordinates (step numbers, attempt indices, process
ids) — never wall-clock — so a chaos test driving two processes under
one :class:`~zookeeper_tpu.resilience.faults.FaultPlan` replays the
same protocol rounds every run. The plan's ``coordinator_loss`` knob
makes the next ``exchange`` raise :class:`CoordinatorLostError`
deterministically, which is how the coordinator-loss recovery legs are
walked in tests.
"""

import json
import logging
import os
import re
import tempfile
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = [
    "CoordinatorLostError",
    "FileCoordinator",
    "HostCoordinator",
    "NullCoordinator",
]


class CoordinatorLostError(RuntimeError):
    """A cross-host round did not complete: a peer never produced its
    half before the deadline (host death, coordinator loss, partitioned
    shared storage) or the loss was injected
    (``FaultPlan.coordinator_loss``). Callers on a RARE path (restore
    agreement) degrade to a loud local decision; callers on a
    MUST-AGREE path (supervisor restart verdicts) propagate — restarting
    half a process group would wedge the survivors in a collective."""


def _safe_key(key: str) -> str:
    """Filesystem-safe exchange key (keys carry step numbers / tiers)."""
    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(key))


def _atomic_write_json(path: str, payload: Any) -> None:
    """temp-file → fsync → atomic rename: a reader either sees the whole
    document or no file — the same finalize discipline the checkpoint
    protocol uses, so a crash mid-publish never leaves a torn round."""
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _read_json(path: str) -> Optional[Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        # Vanished or (impossible post-rename, but belt) torn: absent.
        return None


class HostCoordinator:
    """The coordination interface the resilience stack programs against.

    ``process_index`` / ``process_count`` identify this host in the
    group; ``generation`` namespaces every round (the group supervisor
    sets it to the restart attempt, so attempt N's files can never
    satisfy attempt N+1's rounds).
    """

    process_index: int = 0
    process_count: int = 1
    generation: int = 0

    def exchange(
        self, key: str, payload: Any, timeout_s: Optional[float] = None
    ) -> List[Any]:
        """Allgather one small JSON payload per host for round ``key``;
        returns the payloads ordered by process index. Raises
        :class:`CoordinatorLostError` on deadline."""
        raise NotImplementedError

    def publish_flag(self, kind: str, payload: Any) -> None:
        """Make ``payload`` visible to every host under ``kind``
        (idempotent per host — republish overwrites)."""
        raise NotImplementedError

    def poll_flags(self, kind: str) -> List[Any]:
        """Non-blocking read of every host's published ``kind`` flag
        (ordered by process index; hosts that published nothing are
        simply absent)."""
        raise NotImplementedError


class NullCoordinator(HostCoordinator):
    """Single-process degenerate coordinator: exchanges return the
    caller's own payload, flags are a process-local dict. Lets callers
    wire coordination unconditionally."""

    def __init__(self) -> None:
        self.process_index = 0
        self.process_count = 1
        self.generation = 0
        self._flags: Dict[str, Any] = {}

    def exchange(self, key, payload, timeout_s=None):
        return [payload]

    def publish_flag(self, kind, payload):
        self._flags[str(kind)] = payload

    def poll_flags(self, kind):
        flag = self._flags.get(str(kind))
        return [] if flag is None else [flag]


class FileCoordinator(HostCoordinator):
    """Shared-directory coordinator (see module docstring).

    Layout under ``root``::

        xchg/g<generation>/<key>/r<sequence>/host_<pid>.json
        flags/g<generation>/<kind>/host_<pid>.json

    The per-``key`` sequence counter is process-local and advances once
    per ``exchange`` call: the protocols above are symmetric (every
    host walks the same rounds in the same order), so counters align
    across hosts by construction — the generation namespace catches the
    one asymmetric case, an IN-PROCESS group restart.

    A REAL restart (the whole job killed and respawned over the same
    persistent root) resets both generation and the sequence counters,
    so construction PURGES this host's own files from the root: once
    every host of the new incarnation has constructed its coordinator —
    which happens before any flag poll or exchange, behind the
    ``jax.distributed.initialize`` rendezvous — no stale flag can
    spuriously drain the resumed group and no stale exchange file can
    satisfy a new round. (A dead incarnation's peers never write again,
    so self-purge is safe by construction.)
    """

    def __init__(
        self,
        root: str,
        process_index: int,
        process_count: int,
        *,
        timeout_s: float = 120.0,
        poll_interval_s: float = 0.01,
    ) -> None:
        if not 0 <= int(process_index) < int(process_count):
            raise ValueError(
                f"process_index={process_index} outside "
                f"[0, {process_count})."
            )
        self.root = os.path.abspath(os.path.expanduser(root))
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.generation = 0
        self.timeout_s = float(timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self._seq: Dict[str, int] = {}
        self._purge_own_files()

    def _purge_own_files(self) -> None:
        """Remove every file THIS host wrote in a previous OS
        incarnation (see class docstring). Own files only — peers of a
        live group are never touched."""
        mine = f"host_{self.process_index:05d}.json"
        if not os.path.isdir(self.root):
            return
        for dirpath, _, filenames in os.walk(self.root):
            if mine in filenames:
                try:
                    os.unlink(os.path.join(dirpath, mine))
                except OSError:
                    pass  # racing GC / already gone

    # -- exchange ---------------------------------------------------------

    def _round_dir(self, key: str, seq: int) -> str:
        return os.path.join(
            self.root,
            "xchg",
            f"g{int(self.generation)}",
            _safe_key(key),
            f"r{seq:06d}",
        )

    def exchange(self, key, payload, timeout_s=None):
        from zookeeper_tpu.resilience import faults

        plan = faults.active()
        if plan is not None and plan.take_coordinator_loss():
            raise CoordinatorLostError(
                f"injected coordinator loss during exchange {key!r}"
            )
        seq = self._seq[key] = self._seq.get(key, 0) + 1
        d = self._round_dir(key, seq)
        # Envelope so a JSON-null PAYLOAD is distinguishable from a
        # missing/torn file (exchange(key, None) must still complete).
        _atomic_write_json(
            os.path.join(d, f"host_{self.process_index:05d}.json"),
            {"v": payload},
        )
        deadline = time.monotonic() + (
            self.timeout_s if timeout_s is None else float(timeout_s)
        )
        paths = [
            os.path.join(d, f"host_{pid:05d}.json")
            for pid in range(self.process_count)
        ]
        while True:
            docs = [_read_json(p) if os.path.exists(p) else None for p in paths]
            if all(isinstance(doc, dict) and "v" in doc for doc in docs):
                return [doc["v"] for doc in docs]
            if time.monotonic() >= deadline:
                missing = [
                    pid for pid, doc in enumerate(docs) if doc is None
                ]
                raise CoordinatorLostError(
                    f"exchange {key!r} (round {seq}, generation "
                    f"{self.generation}) timed out waiting for host(s) "
                    f"{missing} of {self.process_count}"
                )
            time.sleep(self.poll_interval_s)

    # -- flags ------------------------------------------------------------

    def _flag_dir(self, kind: str) -> str:
        return os.path.join(
            self.root, "flags", f"g{int(self.generation)}", _safe_key(kind)
        )

    def publish_flag(self, kind, payload):
        _atomic_write_json(
            os.path.join(
                self._flag_dir(kind), f"host_{self.process_index:05d}.json"
            ),
            {"v": payload},
        )

    def poll_flags(self, kind):
        d = self._flag_dir(kind)
        try:
            names = sorted(
                n
                for n in os.listdir(d)
                if n.startswith("host_") and n.endswith(".json")
            )
        except OSError:
            return []
        docs = [_read_json(os.path.join(d, n)) for n in names]
        return [
            doc["v"]
            for doc in docs
            if isinstance(doc, dict) and "v" in doc
        ]
