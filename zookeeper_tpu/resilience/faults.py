"""Deterministic, process-local fault injection.

Every recovery leg in this repo is *exercised*, not just claimed: a test
installs a :class:`FaultPlan` naming exactly which fault fires where
(``kill_at_step=N``, ``corrupt_checkpoint_step=M``, ``fail_save_io=1``,
``nan_at_step=K``, ``serving_worker_crash=1``), runs the real system,
and asserts the recovery contract — e.g. that post-recovery training
state is bit-identical to an uninterrupted run's. The production code
paths carry the (cheap, plan-gated) injection hooks themselves, so the
code that recovers in tests is byte-for-byte the code that recovers in
production; with no plan installed every hook is a single ``None``
check.

Determinism rules:

- Faults key on *logical* coordinates (the global step counter, the
  N-th save attempt, the N-th worker dispatch), never on wall clock —
  two runs of the same plan fire the same faults at the same points.
- One-shot faults (``fail_save_io``, ``serving_worker_crash``, and the
  kill/corrupt triggers) consume themselves, so the *retry* of the
  faulted operation succeeds and the recovery path actually completes.
- The plan is process-local (a module global guarded for thread-safe
  decrement): installing one affects only this process, and ``clear()``
  (or the ``injected()`` context manager) restores a fault-free world.

NaN injection is the one fault that must live *inside* the compiled
step: ``make_train_step`` reads the active plan at trace time and scales
the loss by a ``step == nan_at_step`` selected NaN, so the fault fires
on-device inside a fused ``lax.scan`` slab exactly like a real numeric
blow-up would — no host sync, no recompile of the recovery run.
"""

import contextlib
import os
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional

from zookeeper_tpu.observability import recorder as _recorder
from zookeeper_tpu.observability import trace as _trace


def _injection_event(kind: str, step: Optional[int] = None) -> None:
    """Every fault that actually FIRES marks the host trace, so a
    chaos-test timeline is self-explaining: the injected kill/IO-
    failure/crash appears as an instant event exactly where the
    recovery machinery it triggered starts its spans. It is also a
    flight-recorder trigger (docs/DESIGN.md §16): a chaos leg bundles
    its own evidence, so ``fault_injected{kind}`` timelines come with
    the trace ring + RequestLog that explain them. ``notify`` is one
    global read when no recorder is installed."""
    _trace.event("fault_injected", step=step, attrs={"kind": kind})
    _recorder.notify("fault_injected", step=step, attrs={"kind": kind})


class Preempted(Exception):
    """Training exited at a safe boundary after a preemption request
    (SIGTERM/SIGINT or an injected kill). State as of ``step`` has been
    checkpointed when a checkpoint directory was configured; a
    supervisor (``run_with_recovery``) resumes from it — except for
    SIGINT-caused exits (``signum``), which the supervisor treats as
    the OPERATOR stopping the job, not the pool preempting it."""

    def __init__(self, step: int, saved: bool, signum: Optional[int] = None):
        self.step = int(step)
        self.saved = bool(saved)
        #: The signal that caused the exit (None = injected/programmatic).
        self.signum = signum
        super().__init__(
            f"preempted at step {step} "
            f"({'checkpoint saved' if saved else 'no checkpoint configured'}"
            + (f", signal {signum}" if signum is not None else "")
            + ")"
        )


class NonFiniteLossError(RuntimeError):
    """``nan_policy="halt"``: a non-finite training loss reached a host
    readback boundary. The in-memory state may have skipped the bad
    step(s) but the run refuses to continue; a supervisor restores from
    the last checkpoint."""

    def __init__(self, step: int, skipped: int):
        self.step = int(step)
        self.skipped = int(skipped)
        super().__init__(
            f"non-finite training loss detected by step {step} "
            f"({skipped} step(s) skipped); halting per nan_policy='halt'"
        )


class InjectedFault(OSError):
    """The error raised by plan-driven IO faults — an ``OSError``
    subclass so production retry paths treat it exactly like the disk
    failures it stands in for."""


@dataclass
class FaultPlan:
    """One deterministic schedule of faults. All fields default to
    "never fire"; tests set exactly the legs they walk.

    - ``kill_at_step``: request preemption at the first safe boundary
      whose global step counter is ``>= kill_at_step`` (one-shot — the
      recovery run is not re-killed).
    - ``corrupt_checkpoint_step``: after the save of this step lands,
      scribble its on-disk files so restore sees a torn checkpoint.
    - ``fail_save_io``: the next N checkpoint save attempts raise
      :class:`InjectedFault` (``fail_save_io=1`` == "once": the retry
      succeeds).
    - ``nan_at_step``: the train step whose global step counter equals
      this value computes a NaN loss (traced into the compiled step).
    - ``serving_worker_crash``: the next N MicroBatcher worker dispatch
      iterations crash the worker thread (exercises worker-death
      cleanup + restart).
    - ``decode_worker_crash``: the next N DecodeScheduler loop
      iterations crash mid-decode (exercises clean failure of every
      in-flight token STREAM plus queued requests, and the restart on
      the next submit — the continuous-batching analogue of
      ``serving_worker_crash``).
    - ``fail_async_finalize``: the next N ASYNC checkpoint writes fail
      at the finalize boundary — the data is written but never
      atomically renamed into place, so a torn UNFINALIZED remnant is
      left on disk (exactly the state a crash between write and rename
      leaves) and the write reports failure to the writer's retry loop
      (``fail_async_finalize=1`` == "once": the retry succeeds).
    - ``kill_during_async_write``: the async write of THIS step dies
      mid-write (one-shot, step-keyed like ``corrupt_checkpoint_step``):
      a torn unfinalized remnant is left on disk and the write is
      silently abandoned — no retry, no error to the training thread —
      modeling the process being killed while the background writer was
      mid-save. Restore must land on the previous finalized step.

    Multi-host knobs (docs/DESIGN.md §19) — keyed on LOGICAL host
    coordinates (the jax process index), so an N-process chaos leg
    installs the SAME plan in every process and each host fires only
    its own faults:

    - ``kill_process_at_step``: ``{process_index: step}`` — request
      preemption on exactly that host at the first safe boundary whose
      step counter is ``>= step`` (one-shot per plan, the multi-host
      twin of ``kill_at_step``). Under group recovery the flag
      propagates to a coordinated whole-group save-and-restart.
    - ``fail_host_finalize``: the FIRST per-host sharded-checkpoint
      finalize on this process index is dropped (the host dies between
      writing its shards and the atomic rename): the torn temp dir
      stays, the host marker never appears, and process 0 therefore
      never writes the step's commit record — the step is invisible to
      EVERY host's restore walk. One-shot, NOT retried (a dead host
      does not retry).
    - ``coordinator_loss``: the next N cross-host ``exchange`` rounds
      raise ``CoordinatorLostError`` (the coordinator / shared storage
      partitioned away mid-protocol).

    Disaggregated-serving knobs (docs/DESIGN.md §22) — keyed on the
    N-th page handoff, the deterministic coordinate of the
    prefill→decode seam:

    - ``prefill_role_crash_at``: the N-th decode-slot admission of a
      parked prefill finds the PREFILL role dead mid-handoff (1 = the
      first). The victim and every parked handoff fail clean, the
      prefill pool releases every page (``leak_check() == 0``), and
      active DECODE streams keep decoding — the decode role survived.
    - ``fail_page_transfer``: the next N page transfers fail at the
      move itself (a transient link fault, not a role death): the
      victim stream fails clean, both pools unwind their half of the
      handoff, everything else proceeds.

    Fleet-serving knobs (docs/DESIGN.md §23) — keyed on the N-th
    ROUTED request, the deterministic coordinate of the router →
    replica seam:

    - ``fleet_replica_kill_at``: the replica chosen for the N-th
      routed request (1 = the first) is SIGKILLed before the request
      is forwarded — the in-flight request fails clean with
      ``WorkerCrashedError``, the replica goes unhealthy, and its
      pinned sessions re-route cold to a survivor on their next turn.
    - ``fleet_router_restart_at``: after the N-th routed request
      completes, the HARNESS (test/bench driver — the router cannot
      restart itself, exactly like ``kill_process_at_step``'s group
      supervisor) tears the router down and rebuilds it from its
      persisted ``state_path``; session pins must survive the rebuild.
    - ``delay_forward_ms``: ``{worker_id: ms}`` — GRAY failure: the
      named replica's next forward path stalls for ``ms`` instead of
      dying (one-shot per worker key, coordinate-keyed like
      ``kill_process_at_step``). The worker stays alive and its
      ``/healthz`` keeps passing — this is exactly the case that
      distinguishes the router's latency-tripped circuit breaker
      (docs/DESIGN.md §24) from the liveness probe, which can never
      see a replica that answers probes instantly while poisoning
      every real request.
    """

    kill_at_step: Optional[int] = None
    corrupt_checkpoint_step: Optional[int] = None
    fail_save_io: int = 0
    nan_at_step: Optional[int] = None
    serving_worker_crash: int = 0
    decode_worker_crash: int = 0
    prefill_role_crash_at: Optional[int] = None
    fail_page_transfer: int = 0
    fleet_replica_kill_at: Optional[int] = None
    fleet_router_restart_at: Optional[int] = None
    delay_forward_ms: Optional[Dict[str, int]] = None
    fail_async_finalize: int = 0
    kill_during_async_write: Optional[int] = None
    kill_process_at_step: Optional[Dict[int, int]] = None
    fail_host_finalize: Optional[int] = None
    coordinator_loss: int = 0

    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )
    _killed: bool = field(default=False, repr=False, compare=False)
    _corrupted: bool = field(default=False, repr=False, compare=False)
    _async_killed: bool = field(default=False, repr=False, compare=False)
    _host_finalize_failed: bool = field(
        default=False, repr=False, compare=False
    )
    _handoffs_seen: int = field(default=0, repr=False, compare=False)
    _prefill_role_crashed: bool = field(
        default=False, repr=False, compare=False
    )
    _fleet_kill_seen: int = field(default=0, repr=False, compare=False)
    _fleet_replica_killed: bool = field(
        default=False, repr=False, compare=False
    )
    _fleet_restart_seen: int = field(default=0, repr=False, compare=False)
    _fleet_router_restarted: bool = field(
        default=False, repr=False, compare=False
    )
    _delay_forward_fired: Dict[str, bool] = field(
        default_factory=dict, repr=False, compare=False
    )

    # -- trigger points (called by the production hooks) -----------------

    def kill_due(self, step: int, process_index: int = 0) -> bool:
        """One-shot: True at the first query with ``step >=
        kill_at_step`` (any host), or ``step >=
        kill_process_at_step[process_index]`` (exactly that host).
        Queried at safe boundaries (slab/step ends), so with
        ``unroll > 1`` the kill lands at the end of the slab containing
        the step — the same quantization step-cadence checkpoints
        already have."""
        candidates = [self.kill_at_step]
        if self.kill_process_at_step is not None:
            candidates.append(
                self.kill_process_at_step.get(int(process_index))
            )
        candidates = [c for c in candidates if c is not None]
        if not candidates:
            return False
        # Whichever applicable trigger comes first fires; the one-shot
        # stays plan-wide (one kill per plan, like every other knob).
        due_at = min(candidates)
        with self._lock:
            if not self._killed and int(step) >= int(due_at):
                self._killed = True
                _injection_event("kill_at_step", step=int(step))
                return True
        return False

    def take_host_finalize_failure(self, process_index: int) -> bool:
        """Consume the injected per-host finalize death when it targets
        ``process_index`` (False otherwise / when already fired). The
        caller DROPS the finalize — no marker, no retry — modeling the
        host dying between shard write and atomic rename."""
        if self.fail_host_finalize is None:
            return False
        with self._lock:
            if (
                not self._host_finalize_failed
                and int(process_index) == int(self.fail_host_finalize)
            ):
                self._host_finalize_failed = True
                _injection_event("fail_host_finalize")
                return True
        return False

    def take_coordinator_loss(self) -> bool:
        """Consume one injected cross-host coordinator loss (False when
        exhausted)."""
        with self._lock:
            if self.coordinator_loss > 0:
                self.coordinator_loss -= 1
                _injection_event("coordinator_loss")
                return True
        return False

    def take_save_io_failure(self) -> bool:
        """Consume one injected save-IO failure (False when exhausted)."""
        with self._lock:
            if self.fail_save_io > 0:
                self.fail_save_io -= 1
                _injection_event("fail_save_io")
                return True
        return False

    def take_worker_crash(self) -> bool:
        """Consume one injected serving-worker crash."""
        with self._lock:
            if self.serving_worker_crash > 0:
                self.serving_worker_crash -= 1
                _injection_event("serving_worker_crash")
                return True
        return False

    def take_decode_worker_crash(self) -> bool:
        """Consume one injected decode-scheduler crash."""
        with self._lock:
            if self.decode_worker_crash > 0:
                self.decode_worker_crash -= 1
                _injection_event("decode_worker_crash")
                return True
        return False

    def take_prefill_role_crash(self) -> bool:
        """One-shot, handoff-keyed: True when THIS decode-slot
        admission (the N-th page handoff, counting from 1) should find
        the prefill role dead mid-handoff."""
        if self.prefill_role_crash_at is None:
            return False
        with self._lock:
            self._handoffs_seen += 1
            if (
                not self._prefill_role_crashed
                and self._handoffs_seen >= int(self.prefill_role_crash_at)
            ):
                self._prefill_role_crashed = True
                _injection_event("prefill_role_crash_at")
                return True
        return False

    def take_fleet_replica_kill(self) -> bool:
        """One-shot, routed-request-keyed: True when THIS routed
        request (the N-th, counting from 1) should find its chosen
        replica SIGKILLed before the forward — the router's
        replica-death chaos coordinate (docs/DESIGN.md §23)."""
        if self.fleet_replica_kill_at is None:
            return False
        with self._lock:
            self._fleet_kill_seen += 1
            if (
                not self._fleet_replica_killed
                and self._fleet_kill_seen
                >= int(self.fleet_replica_kill_at)
            ):
                self._fleet_replica_killed = True
                _injection_event("fleet_replica_kill_at")
                return True
        return False

    def take_fleet_router_restart(self) -> bool:
        """One-shot, routed-request-keyed: True after the N-th routed
        request when the HARNESS should tear the router down and
        rebuild it from its persisted state (docs/DESIGN.md §23)."""
        if self.fleet_router_restart_at is None:
            return False
        with self._lock:
            self._fleet_restart_seen += 1
            if (
                not self._fleet_router_restarted
                and self._fleet_restart_seen
                >= int(self.fleet_router_restart_at)
            ):
                self._fleet_router_restarted = True
                _injection_event("fleet_router_restart_at")
                return True
        return False

    def take_delay_forward(self, worker_id: str) -> int:
        """One-shot per worker key: the injected forward-path stall in
        ms for ``worker_id`` (0 = not targeted / already fired). The
        caller SLEEPS for the returned duration inside its forward
        path — latency, not death: liveness probing stays green while
        the request-path latency the circuit breaker watches spikes."""
        if not self.delay_forward_ms:
            return 0
        ms = self.delay_forward_ms.get(str(worker_id))
        if ms is None:
            return 0
        with self._lock:
            if self._delay_forward_fired.get(str(worker_id)):
                return 0
            self._delay_forward_fired[str(worker_id)] = True
        _injection_event("delay_forward_ms")
        return int(ms)

    def take_fail_page_transfer(self) -> bool:
        """Consume one injected page-transfer failure (False when
        exhausted)."""
        with self._lock:
            if self.fail_page_transfer > 0:
                self.fail_page_transfer -= 1
                _injection_event("fail_page_transfer")
                return True
        return False

    def take_async_finalize_failure(self) -> bool:
        """Consume one injected async-finalize failure (False when
        exhausted)."""
        with self._lock:
            if self.fail_async_finalize > 0:
                self.fail_async_finalize -= 1
                _injection_event("fail_async_finalize")
                return True
        return False

    def async_kill_due(self, step: int) -> bool:
        """One-shot: True when the async write of ``step`` should die
        mid-write (torn remnant on disk, write silently abandoned)."""
        if self.kill_during_async_write is None:
            return False
        with self._lock:
            if (
                not self._async_killed
                and int(step) == self.kill_during_async_write
            ):
                self._async_killed = True
                _injection_event("kill_during_async_write", step=int(step))
                return True
        return False

    def corrupt_due(self, step: int) -> bool:
        """One-shot: True when ``step``'s just-landed save should be
        corrupted on disk."""
        if self.corrupt_checkpoint_step is None:
            return False
        with self._lock:
            if not self._corrupted and int(step) == self.corrupt_checkpoint_step:
                self._corrupted = True
                _injection_event("corrupt_checkpoint_step", step=int(step))
                return True
        return False


def corrupt_checkpoint_dir(path: str) -> int:
    """Deterministically tear a checkpoint on disk: every regular file
    under ``path`` is truncated to half and its head overwritten with a
    fixed garbage pattern — the torn-write/partial-flush shape a real
    crash leaves, reproducible bit-for-bit. Returns the number of files
    damaged (0 means ``path`` held nothing to corrupt — callers should
    treat that as a test-setup error, not a survived fault)."""
    damaged = 0
    pattern = b"\xde\xad\xbe\xef" * 16
    for root, _, files in os.walk(path):
        for name in files:
            fpath = os.path.join(root, name)
            try:
                size = os.path.getsize(fpath)
            except OSError:
                continue
            with open(fpath, "r+b" if size else "wb") as f:
                f.truncate(size // 2)
                f.seek(0)
                f.write(pattern[: max(1, min(len(pattern), size // 2 or 1))])
            damaged += 1
    return damaged


# -- process-local activation -------------------------------------------

_ACTIVE: Optional[FaultPlan] = None


def install(plan: FaultPlan) -> FaultPlan:
    """Make ``plan`` the process's active fault plan (replacing any
    prior one). Returns the plan for chaining."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def clear() -> None:
    """Deactivate fault injection (the default, fault-free world)."""
    global _ACTIVE
    _ACTIVE = None


def active() -> Optional[FaultPlan]:
    """The active plan, or None. Production hooks call this and do
    nothing when it is None — the entire overhead of an uninjected
    process is this one attribute read."""
    return _ACTIVE


@contextlib.contextmanager
def injected(plan: FaultPlan) -> Iterator[FaultPlan]:
    """``with injected(FaultPlan(...)) as plan:`` — scoped activation
    that always restores the previous plan (tests can nest)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = plan
    try:
        yield plan
    finally:
        _ACTIVE = prev
