"""The restart supervisor: keep training alive across recoverable exits.

``run_with_recovery(experiment)`` is the production entry point for a
preemptible pool: it runs ``experiment.run()``, and when the run exits
with a *recoverable* status — :class:`Preempted` (SIGTERM / injected
kill, state already checkpointed) or :class:`NonFiniteLossError`
(``nan_policy="halt"``) — it re-runs the experiment, whose own
``Checkpointer.restore_state`` picks up at the last valid checkpoint
(exact mid-epoch resume: step counter + the ``(seed, epoch)``-fixed
pipeline replay). Restarts are budgeted (``max_restarts``) with
exponential backoff so a permanently-broken run fails instead of
spinning, and every restart's *restore latency* (supervisor restart →
first post-resume train step) is measured — the recovery-time number
the failure model in docs/DESIGN.md §10 budgets against.

Unrecoverable exceptions (config errors, structure mismatches, bugs)
propagate immediately: retrying those would replay the same crash
``max_restarts`` times and bury the real traceback.
"""

import logging
import signal as _signal
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from zookeeper_tpu.observability import recorder as _recorder
from zookeeper_tpu.observability import trace as _trace
from zookeeper_tpu.observability.registry import default_registry
from zookeeper_tpu.resilience.coordination import (
    CoordinatorLostError,
    HostCoordinator,
)
from zookeeper_tpu.resilience.faults import NonFiniteLossError, Preempted

logger = logging.getLogger(__name__)


class GroupPeerFailure(RuntimeError):
    """A peer host of the process group exited unrecoverably (or the
    coordinator was lost mid-verdict): the group cannot restart as a
    whole, so THIS host's supervisor stops too instead of re-forming a
    partial cluster that would wedge in its first collective."""

#: Exit statuses a restart can actually fix: the state to resume from is
#: on disk and the cause is transient (preemption) or policy-halted
#: (non-finite loss whose bad step a checkpoint restore discards).
#: A ``Preempted`` carrying SIGINT is excluded at runtime — Ctrl-C is
#: the operator stopping the job, and restarting it would make the run
#: effectively uninterruptible.
RECOVERABLE = (Preempted, NonFiniteLossError)


@dataclass
class RecoveryResult:
    """What the supervisor observed across the whole supervised run."""

    #: The final ``experiment.run()`` return value (training history).
    history: Any
    #: Restarts actually performed (0 = the first run completed).
    restarts: int
    #: The recoverable exceptions that triggered each restart, in order.
    causes: List[BaseException] = field(default_factory=list)
    #: Restore latency per RESUMED run that reached its first train
    #: step: supervisor re-entry -> first post-resume step, ms (the
    #: final successful attempt and any restarted attempt that trained
    #: before being re-preempted). Shorter than ``restarts`` when a
    #: resumed run died before its first step; empty when the
    #: experiment doesn't report first-step timestamps.
    restore_ms: List[float] = field(default_factory=list)
    #: Per PREEMPTED attempt: ms the preemption path spent waiting on
    #: in-flight async checkpoint writes before its final synchronous
    #: save (``PreemptionGuard.preemption_save``; 0.0 under
    #: ``checkpointer.mode="sync"``). The async-mode addition to the
    #: preemption grace-window budget, surfaced alongside
    #: ``restore_ms`` so both halves of the recovery cost are
    #: observable. Empty when the experiment doesn't report it.
    save_wait_ms: List[float] = field(default_factory=list)


def run_with_recovery(
    experiment: Any,
    *,
    max_restarts: int = 3,
    backoff_s: float = 1.0,
    backoff_factor: float = 2.0,
    max_backoff_s: float = 60.0,
    recover_on: Tuple[Type[BaseException], ...] = RECOVERABLE,
    sleep: Callable[[float], None] = time.sleep,
    coordinator: Optional[HostCoordinator] = None,
    group_timeout_s: float = 120.0,
) -> RecoveryResult:
    """Run ``experiment.run()`` under a restart budget.

    ``max_restarts`` bounds the number of RE-runs (so the experiment
    executes at most ``max_restarts + 1`` times); backoff between
    restarts is ``backoff_s * backoff_factor**i`` capped at
    ``max_backoff_s`` (pass ``sleep=lambda s: None`` in tests). When the
    budget is exhausted the last recoverable exception propagates —
    callers distinguish "never recovered" from a hard failure by type.

    The experiment must be restartable-by-rerun: its ``run()`` restores
    from its checkpointer when a checkpoint exists (exactly what
    ``TrainingExperiment`` does). The same experiment OBJECT is reused
    so its configured component tree (checkpoint directory above all)
    carries over.

    **Process-group mode** (docs/DESIGN.md §19): pass a
    ``coordinator`` spanning ``process_count > 1`` hosts — every host
    of the job runs THIS function with its own coordinator instance
    over the same shared root. The coordinator is wired into the
    experiment's boundary check, so any host's SIGTERM / injected kill
    drains and saves ALL hosts at one agreed step; after every exit the
    hosts exchange a restart VERDICT (deadline ``group_timeout_s``) and
    back off the same schedule, so the group re-forms together —
    a bit-identical resume pinned by the multi-process chaos leg. A
    peer that exited unrecoverably (or a lost coordinator) raises
    :class:`GroupPeerFailure` instead of re-forming a partial cluster.
    """
    if max_restarts < 0:
        raise ValueError(f"max_restarts={max_restarts} must be >= 0.")
    if backoff_s < 0 or backoff_factor < 1.0:
        raise ValueError(
            f"backoff_s={backoff_s} must be >= 0 and "
            f"backoff_factor={backoff_factor} >= 1."
        )
    group = (
        coordinator is not None
        and getattr(coordinator, "process_count", 1) > 1
    )
    if group:
        experiment.group_coordinator = coordinator
    causes: List[BaseException] = []
    restore_ms: List[float] = []
    save_wait_ms: List[float] = []
    try:
        for attempt in range(max_restarts + 1):
            if group:
                # Namespace this attempt's flags/exchanges: attempt N's
                # drain files can never satisfy attempt N+1's polls.
                coordinator.generation = attempt
            t_start = time.perf_counter()
            if not group:
                try:
                    history = experiment.run()
                except recover_on as e:
                    _record_save_wait_ms(experiment, e, save_wait_ms)
                    if (
                        isinstance(e, Preempted)
                        and e.signum == _signal.SIGINT
                    ):
                        # Ctrl-C is the OPERATOR stopping the job:
                        # restarting would make the run effectively
                        # uninterruptible. The clean-save-and-exit
                        # already happened; just stop.
                        logger.warning(
                            "SIGINT preemption (operator stop) — not "
                            "restarting: %s",
                            e,
                        )
                        raise
                    causes.append(e)
                    _record_restore_ms(
                        experiment, attempt, t_start, restore_ms
                    )
                    if attempt >= max_restarts:
                        logger.warning(
                            "restart budget exhausted (%d restart(s)); "
                            "last recoverable exit propagates: %s",
                            max_restarts,
                            e,
                        )
                        raise
                    delay = min(
                        max_backoff_s, backoff_s * (backoff_factor**attempt)
                    )
                    logger.warning(
                        "recoverable exit (%s); restart %d/%d after "
                        "%.1fs backoff",
                        e,
                        attempt + 1,
                        max_restarts,
                        delay,
                    )
                    _trace.event(
                        "supervisor_restart",
                        attrs={
                            "attempt": attempt + 1,
                            "cause": type(e).__name__,
                            "backoff_s": delay,
                        },
                    )
                    # One flight-recorder bundle per recovery
                    # (docs/DESIGN.md §16): the state the run died in —
                    # trace ring, metrics, ledger — captured before the
                    # restart overwrites it. One global read when no
                    # recorder is installed.
                    _recorder.notify(
                        "supervisor_restart",
                        step=getattr(e, "step", None),
                        attrs={
                            "attempt": attempt + 1,
                            "cause": type(e).__name__,
                        },
                    )
                    if delay > 0:
                        sleep(delay)
                    continue
                _record_restore_ms(experiment, attempt, t_start, restore_ms)
                if attempt > 0:
                    _trace.event(
                        "supervisor_recovered", attrs={"restarts": attempt}
                    )
                return RecoveryResult(
                    history=history,
                    restarts=attempt,
                    causes=causes,
                    restore_ms=restore_ms,
                    save_wait_ms=save_wait_ms,
                )

            # -- group attempt --------------------------------------------
            history, cause, outcome = None, None, "ok"
            try:
                history = experiment.run()
            except recover_on as e:
                cause = e
                _record_save_wait_ms(experiment, e, save_wait_ms)
                if isinstance(e, Preempted) and e.signum == _signal.SIGINT:
                    # Same operator-stop policy as the single-process
                    # path — and the 'stop' verdict stops the PEERS too.
                    logger.warning(
                        "SIGINT preemption (operator stop) — not "
                        "restarting the group: %s",
                        e,
                    )
                    outcome = "stop"
                else:
                    outcome = "recoverable"
            except BaseException as e:
                # A hard failure must still publish its verdict: peers
                # are waiting in the exchange and would otherwise burn
                # the whole deadline before learning the group is dead.
                cause = e
                outcome = "stop"
            n_restore = len(restore_ms)
            _record_restore_ms(experiment, attempt, t_start, restore_ms)
            if len(restore_ms) > n_restore:
                default_registry().gauge(
                    "zk_group_restore_ms",
                    help="latest group restart -> first post-resume "
                    "train step, ms",
                ).set(restore_ms[-1])
            origin = getattr(
                getattr(experiment, "guard", None), "preemption_origin", None
            )
            try:
                verdicts = coordinator.exchange(
                    "supervisor_verdict",
                    {
                        "outcome": outcome,
                        "cause": type(cause).__name__ if cause else None,
                        "origin": origin,
                    },
                    timeout_s=group_timeout_s,
                )
            except CoordinatorLostError as ce:
                logger.error(
                    "group restart verdict lost (%s); not re-forming a "
                    "partial process group",
                    ce,
                )
                if cause is not None:
                    raise GroupPeerFailure(str(ce)) from cause
                raise GroupPeerFailure(str(ce)) from ce
            outcomes = [v.get("outcome") for v in verdicts]
            if "stop" in outcomes:
                if cause is not None:
                    raise cause
                raise GroupPeerFailure(
                    "peer host(s) exited unrecoverably "
                    f"(verdicts: {outcomes}); this host's run succeeded "
                    "but the group cannot re-form"
                )
            if "recoverable" not in outcomes:
                if attempt > 0:
                    _trace.event(
                        "supervisor_recovered", attrs={"restarts": attempt}
                    )
                return RecoveryResult(
                    history=history,
                    restarts=attempt,
                    causes=causes,
                    restore_ms=restore_ms,
                    save_wait_ms=save_wait_ms,
                )
            if cause is not None:
                causes.append(cause)
            if attempt >= max_restarts:
                logger.warning(
                    "group restart budget exhausted (%d restart(s)); "
                    "last recoverable exit propagates",
                    max_restarts,
                )
                if cause is not None:
                    raise cause
                raise GroupPeerFailure(
                    "group restart budget exhausted while peers still "
                    "want to restart"
                )
            delay = min(max_backoff_s, backoff_s * (backoff_factor**attempt))
            origin_pid = next(
                (
                    v.get("origin")
                    for v in verdicts
                    if v.get("origin") is not None
                ),
                None,
            )
            cause_name = next(
                (v.get("cause") for v in verdicts if v.get("cause")), None
            )
            logger.warning(
                "group recoverable exit (origin host %s, cause %s); "
                "synchronized restart %d/%d after %.1fs backoff",
                origin_pid,
                cause_name,
                attempt + 1,
                max_restarts,
                delay,
            )
            _trace.event(
                "group_restart",
                attrs={
                    "attempt": attempt + 1,
                    "cause": cause_name,
                    "origin": origin_pid,
                    "backoff_s": delay,
                },
            )
            default_registry().counter(
                "zk_group_restarts_total",
                help="coordinated whole-process-group restarts",
            ).inc()
            # Flight-recorder bundle per GROUP recovery, with the
            # triggering host's identity in the manifest: a pod-wide
            # drain names the host that started it (docs/DESIGN.md
            # §16/§19).
            _recorder.notify(
                "group_restart",
                step=getattr(cause, "step", None),
                attrs={
                    "attempt": attempt + 1,
                    "cause": cause_name,
                    "origin": origin_pid,
                    "process_index": coordinator.process_index,
                },
            )
            if delay > 0:
                sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover
    finally:
        if group:
            experiment.group_coordinator = None


def _record_save_wait_ms(
    experiment: Any,
    cause: BaseException,
    save_wait_ms: List[float],
) -> None:
    """Save-wait latency of one PREEMPTED attempt (time the preemption
    path spent draining in-flight async checkpoint writes before its
    final synchronous save), read from the experiment's per-run probe.
    Only ``Preempted`` exits performed a preemption save; other
    recoverable exits carry no sample."""
    if not isinstance(cause, Preempted):
        return
    wait = getattr(experiment, "save_wait_ms", None)
    if wait is not None:
        save_wait_ms.append(float(wait))


def _record_restore_ms(
    experiment: Any,
    attempt: int,
    t_start: float,
    restore_ms: List[float],
) -> None:
    """Restore latency of one RESUMED attempt (restart -> first
    post-resume step), read from the experiment's first-step timestamp
    (``TrainingExperiment`` records one per run). Called for the final
    successful attempt AND for restarted attempts that trained before
    exiting recoverably again; attempt 0 is not a restart."""
    if attempt == 0:
        return  # no restart happened; nothing to attribute
    t_first = getattr(experiment, "first_step_at", None)
    if t_first is not None and t_first >= t_start:
        restore_ms.append((t_first - t_start) * 1e3)


def measure_recovery_restore_ms(
    make_experiment: Callable[[], Any],
    *,
    kill_at_step: int = 2,
    max_restarts: int = 1,
) -> Dict[str, float]:
    """Benchmark harness for the recovery path: run a (small) experiment
    factory under an injected mid-run kill, resume it, and report the
    measured restore latency. ``make_experiment()`` must return a fresh
    experiment configured with a checkpoint directory; the SAME object
    is killed and resumed (matching the in-process supervisor flow).
    Returns ``{"recovery_restore_ms": ..., "recovery_restarts": ...,
    "recovery_save_wait_ms": ...}``.
    """
    from zookeeper_tpu.resilience import faults

    exp = make_experiment()
    with faults.injected(faults.FaultPlan(kill_at_step=kill_at_step)):
        result = run_with_recovery(
            exp, max_restarts=max_restarts, backoff_s=0.0, sleep=lambda s: None
        )
    if result.restarts < 1 or not result.restore_ms:
        raise RuntimeError(
            "recovery measurement never restarted (kill_at_step beyond "
            "the run, or no checkpoint directory configured)"
        )
    return {
        "recovery_restore_ms": round(result.restore_ms[-1], 2),
        "recovery_restarts": float(result.restarts),
        # Time the preemption path waited on in-flight async writes
        # before its final sync save (0.0 under mode="sync") — the
        # other half of the recovery budget.
        "recovery_save_wait_ms": round(
            result.save_wait_ms[-1] if result.save_wait_ms else 0.0, 2
        ),
    }
