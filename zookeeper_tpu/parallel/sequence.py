"""Sequence parallelism as a config-native Partitioner.

Before this module, the dp x sp ring-flash LM recipe — the repo's
long-context flagship (trains s=16k where dense OOMs) — was the ONE
capability not drivable from the ``key=value`` CLI: tests hand-wired
``partial(ring_flash_attention, mesh=..., seq_axis="sp")`` into the
model build. :class:`SequenceParallelPartitioner` closes that seam: it
owns the ``("data", "sp")`` mesh (optionally ``("data", "sp",
"model")`` with ``tp > 1``), shards batches on ``data`` and the
SEQUENCE dimension on ``sp``, and injects the selected
sequence-parallel attention callable into the model build through the
``Partitioner.prepare_model`` hook — so

    python examples/lm_experiment.py TrainLM \\
        partitioner=SequenceParallelPartitioner partitioner.sp=4 ...

trains end-to-end with checkpoint/EMA/metrics/unroll/resume riding
unchanged through ``Experiment.run()``.

Axis ownership (docs/DESIGN.md §11): the PARTITIONER owns the mesh and
the batch/state shardings; the ATTENTION OP owns the sequence-sharded
layout inside its shard_map (ring rotation or all_to_all re-shard); the
MODEL stays mesh-ignorant — it receives an opaque attention callable
and turns its residual-stream activation pins off
(``models.transformer._auto_pin_activations``), because the canonical
batch/channel pin would read ``sp`` as a channel axis and fight the
sequence sharding. Everything between attention calls is an ordinary
pjit program GSPMD lays out from the batch/param shardings.
"""

from typing import Any, Callable, List, Sequence

from jax.sharding import NamedSharding, PartitionSpec

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.parallel.partitioner import MeshPartitioner, _device_mesh
from zookeeper_tpu.parallel.rules import PartitionRule, transformer_tp_rules

#: The attention flavors the ``attention`` Field selects, mapped to
#: their ops-layer entry points (all share the q/k/v [b, s, h, d]
#: global-array contract of ``ops.attention``).
SP_ATTENTION_FLAVORS = ("ring_flash", "ring", "ulysses")


@component
class SequenceParallelPartitioner(MeshPartitioner):
    """dp x sp (x tp) partitioner for sequence-parallel attention models.

    ``sp`` is the sequence-parallel degree (the ring/all_to_all axis);
    ``dp`` the data-parallel degree (-1 infers it from the device
    count); ``tp > 1`` adds a Megatron-style ``model`` axis with
    :func:`transformer_tp_rules` as the default rule table (explicit
    ``with_rules`` overrides). Batches shard ``[batch, seq]`` as
    ``P("data", "sp")`` — the sequence dim is sharded ON THE HOST
    PREFETCH, so no device ever materializes the full sequence of a
    global batch; params and optimizer state replicate over ``data``
    and ``sp`` (shard over ``model`` per the rules).

    Contract: the model must expose ``set_attention_override`` (the
    TransformerLM family does); the global sequence length must divide
    ``sp`` and the global batch must divide ``dp``. Initialization
    dummies (batch 1) fall back to a batch-replicated attention call —
    value-identical, since attention is batch-elementwise.
    """

    #: Sequence-parallel degree; -1 = all devices not taken by dp/tp.
    sp: int = Field(-1)
    #: Data-parallel degree; -1 = inferred from the device count.
    dp: int = Field(-1)
    #: Tensor-parallel degree over a trailing "model" axis (1 = off).
    tp: int = Field(1)
    #: Attention flavor injected into the model: "ring_flash" (flash
    #: kernels inside the ppermute ring — the long-context default),
    #: "ring" (dense block compute), or "ulysses" (all_to_all head
    #: re-shard; needs heads % sp == 0).
    attention: str = Field("ring_flash")
    #: Ulysses' per-device compute: "flash" (long-context) or "dense".
    ulysses_local: str = Field("flash")
    #: Ring schedule: True = double-buffered comm-overlapped prefetch
    #: (bit-identical values; see ops.attention.ring_attention_local),
    #: False = the sequential issue order (A/B timing escape hatch).
    overlap: bool = Field(True)

    data_axes: Sequence[str] = Field(("data",))

    def setup(self) -> None:
        if self._mesh is not None:
            return
        from zookeeper_tpu.core import configured_field_names

        ignored = {"mesh_shape", "mesh_axes", "data_axes"} & set(
            configured_field_names(self)
        )
        if ignored:
            # The inherited MeshPartitioner Fields would be silently
            # ignored (this partitioner derives its mesh from sp/dp/tp)
            # — training on a different layout than the config states.
            raise ValueError(
                f"SequenceParallelPartitioner derives its mesh from "
                f"sp/dp/tp; the configured {sorted(ignored)} would be "
                "ignored. Set partitioner.sp / partitioner.dp / "
                "partitioner.tp instead (or use MeshPartitioner for an "
                "arbitrary layout)."
            )
        if self.attention not in SP_ATTENTION_FLAVORS:
            raise ValueError(
                f"partitioner.attention={self.attention!r} unknown; "
                f"choose one of {'/'.join(SP_ATTENTION_FLAVORS)}."
            )
        if self.ulysses_local not in ("flash", "dense"):
            raise ValueError(
                f"partitioner.ulysses_local={self.ulysses_local!r} "
                "unknown; choose flash/dense."
            )
        # Flavor-inapplicable knobs are the same config-says-one-thing
        # hazard as the inherited mesh Fields above: reject rather than
        # silently ignore.
        explicit = set(configured_field_names(self))
        if self.attention == "ulysses" and "overlap" in explicit:
            raise ValueError(
                "partitioner.overlap only applies to the ring flavors; "
                "attention=ulysses has no ring schedule to overlap."
            )
        if self.attention != "ulysses" and "ulysses_local" in explicit:
            raise ValueError(
                f"partitioner.ulysses_local only applies to "
                f"attention=ulysses (got attention={self.attention!r})."
            )
        if self.tp < 1:
            raise ValueError(f"tp={self.tp} must be >= 1.")
        if self.sp == 0 or self.sp < -1 or self.dp == 0 or self.dp < -1:
            raise ValueError(
                f"sp={self.sp} / dp={self.dp}: expected a positive "
                "degree or -1 (infer)."
            )
        dp, sp = self.dp, self.sp
        if dp == -1 and sp == -1:
            # Wholly unspecified: everything onto the sequence axis —
            # the long-context posture this partitioner exists for.
            dp = 1
        sizes = [dp, sp]
        axes = ["data", "sp"]
        if self.tp > 1:
            sizes.append(self.tp)
            axes.append("model")
        object.__setattr__(
            self,
            "_mesh",
            _device_mesh(tuple(sizes), tuple(axes), self.num_devices),
        )

    @property
    def rules(self) -> List[PartitionRule]:
        override = getattr(self, "_rules_override", None)
        if override is not None:
            return override
        # tp shards the transformer projections Megatron-style by
        # default; without tp everything replicates (pure dp x sp).
        return transformer_tp_rules() if self.tp > 1 else []

    def batch_sharding(self) -> NamedSharding:
        # [batch, seq] token batches: batch over data, SEQUENCE over sp
        # — the host prefetch already lands each device's sequence
        # shard, so the full sequence never materializes per device.
        return NamedSharding(self.mesh, PartitionSpec("data", "sp"))

    def slab_sharding(self) -> NamedSharding:
        # [unroll, batch, seq] slabs: scan axis replicated (the fused
        # multi-step contract), then the batch sharding's layout.
        return NamedSharding(self.mesh, PartitionSpec(None, "data", "sp"))

    def _with_activation_scope(self, fn: Callable) -> Callable:
        # No ambient activation scope: the SP attention op owns the
        # sequence-sharded layout inside its shard_map, and the
        # canonical batch/channel pin would read "sp" (a non-data axis)
        # as a CHANNEL axis and pin d_model over the sequence axis —
        # exactly the fight _auto_pin_activations turns the model-side
        # pins off for. GSPMD propagates the rest from the batch/param
        # shardings.
        return fn

    def attention_callable(self) -> Callable:
        """The injected attention: the Field-selected flavor bound to
        this partitioner's mesh. Resolved lazily per call so the one
        callable serves real batches (batch sharded over ``data``) AND
        init/summary dummies (batch 1, which cannot split over ``data``
        — it runs batch-replicated instead, value-identical because
        attention is batch-elementwise)."""
        from zookeeper_tpu.ops import (
            all_to_all_attention,
            ring_attention,
            ring_flash_attention,
        )

        self.setup()
        mesh = self._mesh
        flavor = self.attention
        local = self.ulysses_local
        overlap = self.overlap

        def sp_attention(q, k, v, *, causal=False, scale=None):
            batch_axis = (
                "data" if q.shape[0] % mesh.shape["data"] == 0 else None
            )
            kw = dict(
                mesh=mesh, seq_axis="sp", batch_axis=batch_axis,
                causal=causal, scale=scale,
            )
            if flavor == "ring_flash":
                return ring_flash_attention(q, k, v, overlap=overlap, **kw)
            if flavor == "ring":
                return ring_attention(q, k, v, overlap=overlap, **kw)
            return all_to_all_attention(q, k, v, local_attention=local, **kw)

        return sp_attention

    def prepare_model(self, model: Any) -> None:
        hook = getattr(model, "set_attention_override", None)
        if hook is None:
            raise ValueError(
                f"SequenceParallelPartitioner requires a model with an "
                f"attention-injection seam (set_attention_override); "
                f"{type(model).__name__} has none. Sequence parallelism "
                "shards the sequence dimension of attention — it cannot "
                "apply to the CNN zoo; use MeshPartitioner/"
                "FsdpPartitioner there."
            )
        hook(self.attention_callable())
