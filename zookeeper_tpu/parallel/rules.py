"""Regex partition rules: param path -> PartitionSpec.

The standard JAX pattern for declaring how each parameter shards over the
mesh (SNIPPETS.md [1] `match_partition_rules`-style, public pattern): rules
are (regex, PartitionSpec) pairs matched against the '/'-joined param path;
first match wins. Used by MeshPartitioner for tensor-parallel / FSDP
layouts while data parallelism needs no rules at all.
"""

import re
from typing import Any, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec

PartitionRule = Tuple[str, PartitionSpec]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):  # DictKey
            parts.append(str(p.key))
        elif hasattr(p, "idx"):  # SequenceKey
            parts.append(str(p.idx))
        elif hasattr(p, "name"):  # GetAttrKey (dataclass fields)
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def match_partition_rules(
    rules: Sequence[PartitionRule], tree: Any
) -> Any:
    """Map every leaf of ``tree`` to the PartitionSpec of the first rule
    whose regex searches its '/'-joined path; unmatched leaves replicate
    (``PartitionSpec()``)."""

    def assign(path, leaf):
        path_s = _path_str(path)
        for pattern, spec in rules:
            if re.search(pattern, path_s):
                return spec
        return PartitionSpec()

    return jax.tree_util.tree_map_with_path(assign, tree)
