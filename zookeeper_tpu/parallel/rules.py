"""Regex partition rules: param path -> PartitionSpec.

The standard JAX pattern for declaring how each parameter shards over the
mesh (SNIPPETS.md [1] `match_partition_rules`-style, public pattern): rules
are (regex, PartitionSpec) pairs matched against the '/'-joined param path;
first match wins. Used by MeshPartitioner for tensor-parallel / FSDP
layouts while data parallelism needs no rules at all.
"""

import re
from typing import Any, List, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec

PartitionRule = Tuple[str, PartitionSpec]


def conv_model_tp_rules(model_axis: str = "model") -> List[PartitionRule]:
    """Tensor-parallel rules for the conv model zoo (QuickNet, Bi-Real-Net,
    BinaryNet, SimpleCnn, ResNet).

    Every conv/dense kernel shards its OUTPUT-feature dim over
    ``model_axis``; per-channel BatchNorm params and batch_stats co-shard
    on the same axis (activations downstream of a sharded conv are
    channel-sharded, so the stats reductions stay local to the shard).
    XLA inserts the input-channel contraction all-reduces per layer —
    standard conv TP over ICI. Rules are matched against full state paths,
    so Adam moments co-shard with their parameters automatically.
    """
    P = PartitionSpec
    return [
        # Depthwise kernels replicate (first match wins — their tied
        # input/output channels would otherwise match the dense-conv
        # rule below and force GSPMD resharding of the grouped conv).
        (r"QuantDepthwiseConv_\d+/", P()),
        # Packed binary kernels [kh, kw, ci_words, co]: shard co.
        (r"kernel_packed$", P(None, None, None, model_axis)),
        (r"kernel_scale$", P(model_axis)),
        # HWIO conv kernels: shard output features.
        (r"(QuantConv|Conv)_\d+/kernel$", P(None, None, None, model_axis)),
        # Dense kernels [in, out]: shard out (incl. the classifier head).
        (r"(QuantDense|Dense)_\d+/kernel$", P(None, model_axis)),
        (r"(QuantDense|Dense)_\d+/bias$", P(model_axis)),
        # Per-channel BN params + running stats co-shard with channels.
        (r"BatchNorm_\d+/(scale|bias)$", P(model_axis)),
        (r"batch_stats/.*/(mean|var)$", P(model_axis)),
    ]


def transformer_tp_rules(model_axis: str = "model") -> List[PartitionRule]:
    """Megatron-style tensor-parallel rules for the TransformerLM
    family: the fused qkv and MLP up projections are COLUMN-parallel
    (output features over ``model_axis``), the attention output and MLP
    down projections ROW-parallel (input features over ``model_axis``)
    — each block then needs exactly one all-reduce per projection pair
    (Korthikanti et al., 2022; XLA inserts it from the shardings).
    Embedding / positional tables and RMSNorm scales replicate (the
    weight-tied LM head reads the replicated embedding). Matched
    against full state paths, so Adam moments co-shard automatically.
    """
    P = PartitionSpec
    # (^|/)-anchored segment names: re.search on '/'-joined paths would
    # otherwise shard any layer merely ENDING in one of these names
    # ('warmup/kernel', 'breakdown/kernel') on the wrong axis, silently.
    return [
        (r"(^|/)(qkv|up)/kernel$", P(None, model_axis)),
        (r"(^|/)(proj|down)/kernel$", P(model_axis, None)),
    ]


def decode_cache_rules(
    data_axes: Sequence[str] = ("data",),
    model_axis: str = None,
) -> List[PartitionRule]:
    """Partition rules for a decode engine's KV-cache state tree
    (``serving.decode``): the per-layer ``k``/``v`` buffers are
    ``[slots, capacity, heads, head_dim]`` — SLOTS shard over the data
    axes (each device owns a contiguous run of sequence slots, exactly
    how a training batch shards) and HEADS over ``model_axis`` when one
    exists (matching :func:`transformer_tp_rules`, whose column-
    parallel qkv kernel produces head-sharded K/V in the first place —
    co-sharding the cache means the decode program writes and reads
    K/V without any resharding collective). Everything else in the
    tree replicates.
    """
    P = PartitionSpec
    return [
        (r"(^|/)(k|v)$", P(tuple(data_axes), None, model_axis, None)),
    ]


def page_pool_rules(
    data_axes: Sequence[str] = ("data",),
    model_axis: str = None,
) -> List[PartitionRule]:
    """Partition rules for a decode engine's SHARED page-pool state
    tree (``serving.decode.pages``, docs/DESIGN.md §20): the per-layer
    ``k``/``v`` pools are ``[num_pages, page_size, heads, head_dim]``
    and — unlike the slot-contiguous cache — the PAGES dimension cannot
    shard over the data axes: any slot may reference any page through
    its page table, so a data-sharded pool would need a cross-device
    gather per read. HEADS shard over ``model_axis`` exactly as in
    :func:`decode_cache_rules` (co-sharded with the column-parallel qkv
    kernel, zero resharding collectives); the int8 scale arrays
    ``[num_pages, page_size, heads]`` co-shard their heads dimension.
    ``data_axes`` is accepted for signature parity (the q/lengths/table
    OPERANDS shard over it — see
    ``ops.sharded_pool_paged_decode_attention``) but the pool state
    itself replicates over it."""
    P = PartitionSpec
    return [
        (r"(^|/)(k|v)$", P(None, None, model_axis, None)),
        (r"(^|/)(k_scale|v_scale)$", P(None, None, model_axis)),
    ]


def auto_fsdp_rules(
    params: Any,
    axis_size: int,
    fsdp_axis: str = "fsdp",
    min_weight_size: int = 2**15,
    replicate_patterns: Sequence[str] = (),
) -> List[PartitionRule]:
    """Generate ZeRO-3-style weight-sharding rules from a params tree.

    Each parameter with at least ``min_weight_size`` elements AND rank
    >= 2 shards its largest ``axis_size``-divisible dimension over
    ``fsdp_axis`` (ties prefer the trailing dim — output features,
    matching the TP layout convention); everything else (biases, BN
    scale/shift — 1-D per-channel vectors) replicates REGARDLESS of
    ``min_weight_size``: the memory saved is negligible, and sharding a
    per-channel vector makes its weight-gradient reduction want a
    channel-sharded activation cotangent, which GSPMD can only reach
    from the batch-sharded layout by full rematerialization (the
    "[SPMD] Involuntary full rematerialization" warning observed on
    BatchNorm backward under FSDP). Rules are suffix-anchored on the
    params-relative path, so optimizer moments and EMA copies co-shard
    with their parameter automatically.

    This is the standard JAX FSDP recipe (scaling-book style): with the
    batch sharded over the SAME mesh axis, XLA all-gathers each layer's
    weights on use (fwd + bwd) and reduce-scatters its gradients —
    per-device param/optimizer memory drops ~axis_size-fold for the
    sharded weights, paid for with weight all-gather traffic over ICI.

    ``replicate_patterns``: regexes over params-relative paths forced to
    replicate regardless of size; matched with ``re.search``, so anchor
    them (``"^Conv_1/"``) — a bare ``"Conv_1/"`` also matches inside
    ``"QuantConv_1/kernel"``. The known case that needs it: a LARGE
    grouped/depthwise conv kernel — its weight gradient lowers to a
    ``batch_group_count`` conv whose GSPMD partitioning demands a
    channel-sharded cotangent, reachable from the batch-sharded layout
    only by full rematerialization (same pathology class the TP rules
    dodge by replicating ``QuantDepthwiseConv``). Grouped kernels below
    ``min_weight_size`` (typical stems) replicate naturally.
    """
    from math import prod

    from flax import traverse_util

    replicate_res = [re.compile(p) for p in replicate_patterns]
    flat = traverse_util.flatten_dict(params, sep="/")
    rules: List[PartitionRule] = []
    for path, leaf in flat.items():
        shape = tuple(getattr(leaf, "shape", ()))
        size = prod(shape) if shape else 0
        spec = PartitionSpec()
        forced = any(r.search(path) for r in replicate_res)
        if not forced and size >= min_weight_size and len(shape) >= 2:
            best = None
            for i, d in enumerate(shape):
                if d % axis_size == 0 and (best is None or d >= shape[best]):
                    best = i
            if best is not None:
                spec = PartitionSpec(
                    *[
                        fsdp_axis if i == best else None
                        for i in range(len(shape))
                    ]
                )
        # EVERY param gets its own explicit rule (small ones an explicit
        # replicate), and rules sort deepest-first below: a nested path
        # like "Head_0/Dense_0/kernel" then always hits its own rule
        # before a shallower param's suffix rule ("Dense_0/kernel") could
        # capture it. The (^|/) left boundary blocks same-segment prefix
        # capture ("QuantDense_0" vs "Dense_0").
        rules.append(((r"(^|/)" + re.escape(path) + "$"), spec))
    # Deepest-first: a path is never shadowed by a strict suffix of
    # itself (which necessarily has fewer segments).
    rules.sort(key=lambda r: -r[0].count("/"))
    return rules


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):  # DictKey
            parts.append(str(p.key))
        elif hasattr(p, "idx"):  # SequenceKey
            parts.append(str(p.idx))
        elif hasattr(p, "name"):  # GetAttrKey (dataclass fields)
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def match_partition_rules(
    rules: Sequence[PartitionRule], tree: Any
) -> Any:
    """Map every leaf of ``tree`` to the PartitionSpec of the first rule
    whose regex searches its '/'-joined path; unmatched leaves replicate
    (``PartitionSpec()``)."""

    def assign(path, leaf):
        path_s = _path_str(path)
        for pattern, spec in rules:
            if re.search(pattern, path_s):
                return spec
        return PartitionSpec()

    return jax.tree_util.tree_map_with_path(assign, tree)
