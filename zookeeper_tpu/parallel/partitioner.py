"""Partitioner components: who owns the mesh and the shardings.

SNIPPETS.md [3]-style ``Partitioner`` abstraction (public pattern): the
training loop asks the partitioner to (a) place the initial state, (b)
provide the batch sharding for host->device prefetch, and (c) compile the
step function. Everything else — collectives, replication, donation — is
derived by XLA from the shardings.

- ``SingleDevicePartitioner``: plain ``jax.jit`` on the default device
  (BASELINE config #1, CPU/1-chip path).
- ``DataParallelPartitioner``: 1-D mesh over all devices, batch sharded on
  the ``data`` axis, state replicated; XLA inserts the gradient all-reduce
  over ICI (the MirroredStrategy+NCCL equivalent, SURVEY.md §2.5).
- ``MeshPartitioner``: general N-D mesh (``data``/``fsdp``/``model`` axes)
  with regex partition rules for tensor-parallel / FSDP layouts and batch
  sharded over all data-like axes.
"""

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.observability.ledger import LedgeredExecutable
from zookeeper_tpu.parallel.rules import PartitionRule, match_partition_rules


@component
class Partitioner:
    """Abstract distribution strategy."""

    def setup(self) -> None:
        """Create the mesh (if any). Idempotent."""

    def _ledgered(self, kind: str, jitted: Any) -> LedgeredExecutable:
        """Wrap a compiled-seam callable so its (lazy) lower + compile
        is timed and recorded in the process program ledger
        (docs/DESIGN.md §14): identity key, XLA cost-analysis FLOPs,
        compile wall time, compiled memory analysis. The wrapper's
        steady-state dispatch is the AOT-compiled executable — the
        same program the jit would have cached, one attribute read
        away."""
        mesh = self.mesh
        mesh_desc = (
            "x".join(f"{k}:{v}" for k, v in mesh.shape.items())
            if mesh is not None
            else "1"
        )
        return LedgeredExecutable(
            jitted,
            kind=kind,
            key=f"{type(self).__name__}/mesh={mesh_desc}",
            attrs={"partitioner": type(self).__name__},
        )

    @property
    def mesh(self) -> Optional[Mesh]:
        return None

    def process_span(self) -> int:
        """How many DISTINCT JAX processes this partitioner's mesh
        spans (1 = meshless or single-host). The resilience stack keys
        on it: per-host sharded checkpointing and group recovery only
        engage when state/collectives actually cross a process
        boundary, and the multi-process dryrun asserts its mesh spans
        the whole group."""
        mesh = self.mesh
        if mesh is None:
            return 1
        return len({d.process_index for d in mesh.devices.flat})

    def prepare_model(self, model: Any) -> None:
        """Hook called before ``model.build()`` (the experiment does it
        in ``build_state``): a partitioner that owns part of the MODEL
        program — e.g. ``SequenceParallelPartitioner`` injecting its
        mesh-bound attention callable — wires it here, so recipes stay
        config-first instead of hand-wiring callables into models.
        Default: no-op."""

    def batch_sharding(self) -> Optional[NamedSharding]:
        """Sharding for host->device prefetch of batches (None = default
        device placement)."""
        return None

    def slab_sharding(self) -> Optional[NamedSharding]:
        """Sharding for ``[unroll, batch, ...]`` SLABS (the fused
        multi-step loop's input unit): the leading unroll axis is the
        scan dimension and stays unsharded; the batch axis (now axis 1)
        carries the data-parallel sharding. None = default placement."""
        return None

    def shard_state(self, state: Any) -> Any:
        """Place the freshly-initialized state onto devices."""
        return state

    def state_sharding(self, state: Any) -> Any:
        """Sharding pytree (or prefix) describing the placed state."""
        return None

    def compile_step(
        self, step_fn: Callable, state: Any, *, donate_state: bool = True
    ) -> Callable:
        """Compile ``(state, batch) -> (state, metrics)``."""
        raise NotImplementedError

    def compile_multi_step(
        self,
        multi_step_fn: Callable,
        state: Any,
        *,
        donate_state: bool = True,
        donate_slab: bool = False,
    ) -> Callable:
        """Compile a fused ``(state, slab) -> (state, stacked_metrics)``
        multi-step (``training.step.build_multi_step`` output).
        ``donate_slab`` stays off by default: donation is input->OUTPUT
        aliasing, and no output shares the slab's ``[unroll, batch,
        ...]`` shape, so donating it buys nothing and XLA warns on
        every compile. The slab's HBM frees normally when the loop
        drops its reference after the dispatch."""
        raise NotImplementedError

    def compile_eval(self, eval_fn: Callable, state: Any) -> Callable:
        """Compile ``(state, batch) -> metrics``."""
        raise NotImplementedError

    def variables_sharding(self, variables: Any) -> Any:
        """Sharding pytree for an inference variables dict
        (``{"params": ..., **model_state}`` — no optimizer state). Paths
        match the same partition rules as training state (``params/...``
        prefixes are identical), so a model serves under the layout it
        trained with. None = default placement."""
        return None

    def compile_forward(
        self, forward_fn: Callable, variables: Any, *,
        batch_rows: Optional[int] = None,
    ) -> Callable:
        """Compile an inference forward ``(variables, batch) -> outputs``
        for the serving engine. DONATION-SAFE by contract: unlike the
        train step's consumed state, the variables serve every subsequent
        request and must never be donated; the batch is not donated
        either (no output aliases its shape — donating would buy nothing
        and warn on every compile, the ``donate_slab`` lesson).

        ``batch_rows`` is the concrete bucket size being compiled (the
        serving engine compiles per shape bucket, so it always knows):
        mesh partitioners use it to fall back to a REPLICATED batch when
        the bucket cannot split over the data axes (a 1-row request on
        an 8-way mesh) — correct everywhere, wasteful only on the small
        buckets; size the bucket ladder in multiples of the data-axis
        product to serve fully sharded."""
        raise NotImplementedError

    def decode_cache_axes(self) -> Tuple[Tuple[str, ...], Optional[str]]:
        """``(data_axes, model_axis)`` the decode KV cache shards over
        — the ONE derivation both :meth:`decode_cache_sharding` and the
        decode engine's sharded attention wrapper
        (``ops.sharded_paged_decode_attention``) consume: if the two
        disagreed, GSPMD would reshard/gather the cache around the
        kernel on every decode step — token-correct output, silently
        wrong bytes. Default (no mesh): nothing to shard over."""
        return (), None

    def decode_cache_sharding(self, cache: Any) -> Any:
        """Sharding pytree for a decode engine's KV-cache state
        (``serving.decode``): per-layer ``k``/``v`` buffers ``[slots,
        capacity, heads, head_dim]``. None = default placement (single
        device); mesh partitioners shard slots over the data axes and
        heads over the model axis via
        :func:`zookeeper_tpu.parallel.rules.decode_cache_rules`. The
        ENGINE checks divisibility (slots vs the data-axis product,
        heads vs the model axis) and falls back to replicated cache
        state when the shapes cannot split — the same degrade-don't-die
        posture ``compile_forward``'s small buckets take."""
        return None

    def page_pool_sharding(self, pool: Any) -> Any:
        """Sharding pytree for a decode engine's SHARED page-pool state
        (``kv_layout="paged"``, docs/DESIGN.md §20): per-layer
        ``k``/``v`` pools ``[num_pages, page_size, heads, head_dim]``
        (+ int8 scale arrays). Pages replicate over the data axes (any
        slot references any page), heads shard over the model axis via
        :func:`zookeeper_tpu.parallel.rules.page_pool_rules`; the
        engine applies the same divisibility check + replicated
        fallback as :meth:`decode_cache_sharding`. None = default
        placement."""
        return None


@component
class SingleDevicePartitioner(Partitioner):
    """Plain jit on the default device."""

    def compile_step(self, step_fn, state, *, donate_state: bool = True):
        return self._ledgered(
            "train_step",
            jax.jit(step_fn, donate_argnums=(0,) if donate_state else ()),
        )

    def compile_multi_step(
        self,
        multi_step_fn,
        state,
        *,
        donate_state: bool = True,
        donate_slab: bool = False,
    ):
        donate = tuple(
            i
            for i, d in enumerate((donate_state, donate_slab))
            if d
        )
        return self._ledgered(
            "multi_step", jax.jit(multi_step_fn, donate_argnums=donate)
        )

    def compile_eval(self, eval_fn, state):
        return self._ledgered("eval_step", jax.jit(eval_fn))

    def compile_forward(self, forward_fn, variables, *, batch_rows=None):
        return jax.jit(forward_fn)


def _device_mesh(
    axis_sizes: Sequence[int],
    axis_names: Sequence[str],
    num_devices: int = -1,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build a mesh over the first ``num_devices`` devices (-1 = all).
    ``-1`` in ``axis_sizes`` infers that axis from the device count (like
    reshape). An explicit ``devices`` list overrides both — the
    role-aware seam (docs/DESIGN.md §22): a disaggregated topology
    carves the host's devices into disjoint prefill/decode slices, so
    "first N" cannot express the second role's slice."""
    all_devices = (
        list(devices) if devices is not None else jax.devices()
    )
    if devices is None and num_devices > 0:
        if num_devices > len(all_devices):
            raise ValueError(
                f"Requested {num_devices} devices, have {len(all_devices)}."
            )
        all_devices = all_devices[:num_devices]
    devices = np.asarray(all_devices)
    n = devices.size
    sizes = list(axis_sizes)
    if sizes.count(-1) > 1:
        raise ValueError("At most one mesh axis may be -1.")
    known = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if -1 in sizes:
        if n % known != 0:
            raise ValueError(
                f"Device count {n} not divisible by fixed axes {known}."
            )
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"Mesh {dict(zip(axis_names, sizes))} needs "
            f"{int(np.prod(sizes))} devices, have {n}."
        )
    try:
        from jax.experimental import mesh_utils

        # Pass the (possibly subset) device list explicitly: without it,
        # create_device_mesh sizes itself against the full host and always
        # fails for subsets, losing ICI-topology-aware placement.
        dev_array = mesh_utils.create_device_mesh(sizes, devices=list(devices))
    except (ValueError, NotImplementedError) as e:
        # Only the no-known-good-assignment case falls back; anything else
        # should surface. The naive order loses ICI-topology awareness, so
        # say so.
        import warnings

        warnings.warn(
            f"mesh_utils.create_device_mesh failed ({e}); falling back to "
            "enumeration-order device layout, which may place mesh "
            "neighbors across slow ICI links.",
            stacklevel=2,
        )
        dev_array = devices.reshape(sizes)
    return Mesh(dev_array, tuple(axis_names))


@component
class MeshPartitioner(Partitioner):
    """General N-D mesh partitioner.

    ``mesh_shape``/``mesh_axes`` define the mesh (e.g. ``(-1, 8)`` with
    ``('data', 'model')``); ``data_axes`` names the axes the batch dimension
    is sharded over (DP and FSDP axes both carry batch); ``rules`` maps
    param paths to PartitionSpecs (empty = fully replicated params).
    """

    mesh_shape: Sequence[int] = Field((-1,))
    mesh_axes: Sequence[str] = Field(("data",))
    data_axes: Sequence[str] = Field(("data",))
    #: Use only the first N devices (-1 = all); lets dry runs build an
    #: n-device mesh on hosts exposing more.
    num_devices: int = Field(-1)

    _mesh: Optional[Mesh] = None
    _rules: List[PartitionRule] = []

    def with_rules(self, rules: Sequence[PartitionRule]) -> "MeshPartitioner":
        """Set param partition rules (programmatic, since PartitionSpecs are
        not CLI-expressible). Returns self for chaining."""
        object.__setattr__(self, "_rules_override", list(rules))
        return self

    def with_devices(self, devices: Sequence[Any]) -> "MeshPartitioner":
        """Pin the mesh to an EXPLICIT device list (programmatic, like
        ``with_rules`` — device objects are not CLI-expressible):
        the role-aware seam a :class:`~zookeeper_tpu.serving.disagg.\
partition.DisaggPartitioner` uses to put its prefill and decode roles
        on disjoint device slices. Must be called before the mesh is
        built. Returns self for chaining."""
        if self._mesh is not None:
            raise RuntimeError(
                "with_devices after the mesh was built; pin devices "
                "before the first setup()/mesh access."
            )
        object.__setattr__(self, "_devices_override", list(devices))
        return self

    @property
    def rules(self) -> List[PartitionRule]:
        return getattr(self, "_rules_override", self._rules)

    def setup(self) -> None:
        if self._mesh is None:
            object.__setattr__(
                self,
                "_mesh",
                _device_mesh(
                    tuple(self.mesh_shape),
                    tuple(self.mesh_axes),
                    self.num_devices,
                    devices=getattr(self, "_devices_override", None),
                ),
            )

    @property
    def mesh(self) -> Optional[Mesh]:
        self.setup()
        return self._mesh

    def batch_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(tuple(self.data_axes)))

    def slab_sharding(self) -> NamedSharding:
        # Leading unroll (scan) axis replicated, batch axis sharded over
        # the data axes — each device holds its batch slice of EVERY
        # step in the slab, so the scanned per-step batch carries
        # exactly the batch_sharding() layout.
        return NamedSharding(
            self.mesh, PartitionSpec(None, tuple(self.data_axes))
        )

    def state_sharding(self, state: Any) -> Any:
        """Per-leaf shardings for the whole TrainState.

        The partition rules are matched against full state paths
        (``params/Dense_0/kernel``, ``opt_state/0/mu/Dense_0/kernel``), so
        a rule like ``("kernel", P(None, "model"))`` shards the parameter
        AND its Adam moments identically — which is exactly the invariant
        sharded optimizers need. Unmatched leaves (step, batch_stats,
        counters) replicate.
        """
        return self._sharding_from_rules(state, self.rules)

    def _sharding_from_rules(
        self, state: Any, rules: Sequence[PartitionRule]
    ) -> Any:
        mesh = self.mesh
        specs = match_partition_rules(rules, state)
        return jax.tree.map(lambda s: NamedSharding(mesh, s), specs)

    def shard_state(self, state: Any) -> Any:
        sharding = self.state_sharding(state)
        if self.process_span() > 1:
            # Cross-process mesh: device_put of a host-local value onto
            # a non-addressable sharding asserts value equality via a
            # collective broadcast — unsupported on CPU clusters and
            # wasted work on pods. Every process initialized the SAME
            # state (same seed — the determinism contract), so each
            # assembles the global array from its own local copy
            # instead, shard by addressable shard.
            def place(x, s):
                arr = np.asarray(x)
                return jax.make_array_from_callback(
                    arr.shape, s, lambda idx: arr[idx]
                )

            return jax.tree.map(place, state, sharding)
        return jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            state,
            sharding,
        )

    def _with_activation_scope(self, fn: Callable) -> Callable:
        """Wrap ``fn`` so it traces inside this mesh's activation-sharding
        scope: layer code (Quant* layers) pins batch-dim activation
        shardings to the data axes via
        :func:`zookeeper_tpu.parallel.sharding.constrain_batch_sharded`,
        which keeps GSPMD from spreading the batch over non-data axes in
        the backward (the dp×tp involuntary-rematerialization trigger —
        see that module's docstring)."""
        import functools

        from zookeeper_tpu.parallel.sharding import activation_sharding_scope

        mesh, data_axes = self.mesh, tuple(self.data_axes)
        # Non-data mesh axes carry tensor-parallel channel shardings.
        model_axes = tuple(
            a for a in self.mesh_axes if a not in set(data_axes)
        )

        @functools.wraps(fn)
        def scoped(*args, **kwargs):
            with activation_sharding_scope(mesh, data_axes, model_axes):
                return fn(*args, **kwargs)

        return scoped

    def compile_step(self, step_fn, state, *, donate_state: bool = True):
        state_sh = self.state_sharding(state)
        batch_sh = self.batch_sharding()
        metrics_sh = NamedSharding(self.mesh, PartitionSpec())
        return self._ledgered(
            "train_step",
            jax.jit(
                self._with_activation_scope(step_fn),
                in_shardings=(state_sh, batch_sh),
                out_shardings=(state_sh, metrics_sh),
                donate_argnums=(0,) if donate_state else (),
            ),
        )

    def compile_multi_step(
        self,
        multi_step_fn,
        state,
        *,
        donate_state: bool = True,
        donate_slab: bool = False,
    ):
        state_sh = self.state_sharding(state)
        slab_sh = self.slab_sharding()
        # Stacked [unroll] per-step metrics replicate like the single
        # step's scalars (PartitionSpec() is rank-agnostic).
        metrics_sh = NamedSharding(self.mesh, PartitionSpec())
        donate = tuple(
            i for i, d in enumerate((donate_state, donate_slab)) if d
        )
        return self._ledgered(
            "multi_step",
            jax.jit(
                self._with_activation_scope(multi_step_fn),
                in_shardings=(state_sh, slab_sh),
                out_shardings=(state_sh, metrics_sh),
                donate_argnums=donate,
            ),
        )

    def compile_eval(self, eval_fn, state):
        state_sh = self.state_sharding(state)
        batch_sh = self.batch_sharding()
        return self._ledgered(
            "eval_step",
            jax.jit(
                self._with_activation_scope(eval_fn),
                in_shardings=(state_sh, batch_sh),
                out_shardings=NamedSharding(self.mesh, PartitionSpec()),
            ),
        )

    def variables_sharding(self, variables: Any) -> Any:
        # Same rule table as training state: rules are matched against
        # full paths, and an inference dict's ``params/...`` /
        # ``batch_stats/...`` paths are exactly the training prefixes.
        return self._sharding_from_rules(variables, self.rules)

    def decode_cache_axes(self):
        data_axes = tuple(self.data_axes)
        model_axes = tuple(
            a for a in self.mesh_axes if a not in set(data_axes)
        )
        return data_axes, (model_axes[0] if model_axes else None)

    def decode_cache_sharding(self, cache: Any) -> Any:
        from zookeeper_tpu.parallel.rules import decode_cache_rules

        data_axes, model_axis = self.decode_cache_axes()
        rules = decode_cache_rules(data_axes, model_axis)
        return self._sharding_from_rules(cache, rules)

    def page_pool_sharding(self, pool: Any) -> Any:
        from zookeeper_tpu.parallel.rules import page_pool_rules

        data_axes, model_axis = self.decode_cache_axes()
        rules = page_pool_rules(data_axes, model_axis)
        return self._sharding_from_rules(pool, rules)

    def compile_forward(self, forward_fn, variables, *, batch_rows=None):
        vars_sh = self.variables_sharding(variables)
        batch_sh = self.batch_sharding()
        scoped = self._with_activation_scope(forward_fn)
        if batch_rows is not None:
            total = int(
                np.prod([self.mesh.shape[a] for a in self.data_axes])
            )
            if batch_rows % total != 0:
                # A bucket that cannot split over the data axes (e.g.
                # the 1-row bucket on an 8-way mesh) runs REPLICATED —
                # every device computes the whole small batch. Correct
                # always; only the sub-mesh buckets pay the redundancy.
                # The activation scope would re-pin batch dims to the
                # data axes inside the trace and fight the replicated
                # in_sharding, so it is dropped for these buckets.
                repl = NamedSharding(self.mesh, PartitionSpec())
                return jax.jit(
                    forward_fn,
                    in_shardings=(vars_sh, repl),
                    out_shardings=repl,
                )
        # Outputs keep the batch-sharded layout (PartitionSpec is
        # rank-agnostic on trailing dims): the serving readback slices
        # per-request rows on host, so replicating (an all-gather) would
        # be pure waste. No donation — see the base-class contract.
        return jax.jit(
            scoped,
            in_shardings=(vars_sh, batch_sh),
            out_shardings=batch_sh,
        )


@component
class DataParallelPartitioner(MeshPartitioner):
    """Pure DP: 1-D mesh, batch on 'data', everything replicated (the
    MeshPartitioner defaults, under the name users reach for)."""


@component
class FsdpPartitioner(MeshPartitioner):
    """Turnkey FSDP: 1-D mesh, batch AND large weights sharded over the
    same ``fsdp`` axis (ZeRO-3-style — see
    :func:`zookeeper_tpu.parallel.rules.auto_fsdp_rules`). Per-device
    param + optimizer memory drops ~N-fold for the sharded weights; XLA
    inserts the per-layer weight all-gathers and gradient
    reduce-scatters over ICI. Explicit ``with_rules`` overrides the
    auto-generated layout.
    """

    mesh_shape: Sequence[int] = Field((-1,))
    mesh_axes: Sequence[str] = Field(("fsdp",))
    data_axes: Sequence[str] = Field(("fsdp",))
    #: Parameters below this many ELEMENTS replicate (biases, BN):
    #: sharding tiny tensors costs more collective latency than it saves.
    min_weight_size: int = Field(2**15)
    #: Regexes over params-relative paths forced to replicate regardless
    #: of size — the escape hatch for large grouped/depthwise conv
    #: kernels, whose FSDP-sharded weight gradients hit a GSPMD
    #: full-rematerialization reshard (see rules.auto_fsdp_rules).
    replicate_patterns: Sequence[str] = Field(())

    def _auto_rules(self, params: Any) -> List[PartitionRule]:
        from zookeeper_tpu.parallel.rules import auto_fsdp_rules

        axis = tuple(self.mesh_axes)[0]
        return auto_fsdp_rules(
            params,
            axis_size=self.mesh.shape[axis],
            fsdp_axis=axis,
            min_weight_size=self.min_weight_size,
            replicate_patterns=tuple(self.replicate_patterns),
        )

    def state_sharding(self, state: Any) -> Any:
        # An explicit with_rules (even an empty list = replicate all)
        # always wins; otherwise rules derive from THIS state's params on
        # every call — no caching, so reusing one partitioner across
        # differently-shaped states cannot silently apply stale rules.
        if getattr(self, "_rules_override", None) is not None:
            return super().state_sharding(state)
        return self._sharding_from_rules(state, self._auto_rules(state.params))

    def variables_sharding(self, variables: Any) -> Any:
        # Serving under FSDP: derive the same auto layout from the
        # inference dict's params (suffix-anchored rules, so the
        # ``params/`` prefix matches like training state paths).
        if getattr(self, "_rules_override", None) is not None:
            return super().variables_sharding(variables)
        return self._sharding_from_rules(
            variables, self._auto_rules(variables["params"])
        )
