"""Activation-sharding constraints: the framework lever GSPMD needs.

Partition RULES govern parameters; activation layouts are otherwise
compiler-chosen, and on dp×tp meshes GSPMD sometimes picks a layout it
then cannot reshard efficiently — the observed failure is the BatchNorm
backward's gradient accumulation getting its batch dimension spread over
ALL mesh axes and triggering an "[SPMD] Involuntary full
rematerialization" (replicate-then-repartition) warning. The standard
fix (How to Scale Your Model recipe: annotate, don't hand-schedule) is
``jax.lax.with_sharding_constraint`` pinning activations to the
canonical dp×tp layout.

This module provides the ambient plumbing so model/layer code can request
that pin WITHOUT knowing about meshes: the partitioner opens an
:func:`activation_sharding_scope` around step tracing, and the Quant*
layers / the sharded ``BatchNorm`` (plus anything else that calls
:func:`constrain_batch_sharded`) pin activations to

    ``P(data_axes, None, ..., None, model_axes)``

— batch on the data axes, trailing (channel) dimension on the
tensor-parallel axes (matching TP rules that shard kernels on the output
-feature dim and co-shard BN params), everything else replicated. The
spec is fully CLOSED deliberately: an open/UNCONSTRAINED dim is
refinable during propagation, and the propagator was observed refining a
"batch on data" pin into batch-over-all-axes — recreating the exact
resharding the pin exists to prevent. Outside a scope — single-device
jit, eager debugging, tests — the helper is an exact no-op.
"""

from contextlib import contextmanager
from contextvars import ContextVar
from typing import Optional, Sequence, Tuple

_SCOPE: ContextVar[
    Optional[Tuple[object, Tuple[str, ...], Tuple[str, ...]]]
] = ContextVar("zk_activation_sharding_scope", default=None)


@contextmanager
def activation_sharding_scope(
    mesh, data_axes: Sequence[str], model_axes: Sequence[str] = ()
):
    """Make ``(mesh, data_axes, model_axes)`` ambient for
    :func:`constrain_batch_sharded`.

    Opened by the mesh partitioners around step tracing (the scope must be
    active while JAX traces the step function, which is when the layer
    code actually runs). Re-entrant; the innermost scope wins.
    """
    token = _SCOPE.set((mesh, tuple(data_axes), tuple(model_axes)))
    try:
        yield
    finally:
        _SCOPE.reset(token)


def current_activation_scope():
    """The active ``(mesh, data_axes, model_axes)`` or None."""
    return _SCOPE.get()


def constrain_batch_sharded(x):
    """Pin ``x`` to the ambient canonical activation layout: dim 0
    (batch) on the data axes, the last dim (channels) on the model axes
    (replicated when the scope has none, e.g. pure DP / FSDP), middle
    dims replicated. Applies to the cotangent too (the constraint
    transposes). No-op when no scope is active or ``x`` has fewer than
    two dims (a 1-D tensor is a per-channel vector, not a batched
    activation — pinning its only dim to the data axes would be a
    nonsensical layout).
    """
    scope = _SCOPE.get()
    if scope is None or getattr(x, "ndim", 0) < 2:
        return x
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh, data_axes, model_axes = scope
    chan = model_axes if model_axes else None
    spec = PartitionSpec(data_axes, *([None] * (x.ndim - 2)), chan)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
