"""Multi-host (pod) runtime initialization.

The distributed communication backend (SURVEY.md §2.5): collectives are
XLA-compiled from sharding annotations and ride ICI within a pod slice and
DCN across slices — there is no hand-written NCCL/MPI-style layer, by
design. What remains host-side is bootstrapping the JAX distributed
runtime so all processes agree on topology, which this module owns, plus
small helpers for process-level facts the data pipeline needs.

Failure/recovery model (SURVEY.md §5): crash-restart with deterministic
resume — a failed pod job restarts, ``jax.distributed.initialize`` re-forms
the cluster, and the Experiment restores the latest orbax checkpoint; the
(seed, epoch)-keyed data pipeline makes the replay exact.
"""

from typing import Optional

from zookeeper_tpu.core import Field, component


def is_distributed_initialized() -> bool:
    """Whether the JAX distributed runtime is already up.

    Prefers the PUBLIC ``jax.distributed.is_initialized()`` (added in
    recent jax); falls back to probing the private
    ``jax._src.distributed.global_state`` only when the public API is
    absent — the private module layout is version-fragile and must not
    be the first thing this code reaches for."""
    import jax

    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        try:
            return bool(probe())
        except Exception:  # pragma: no cover - defensive, API churn
            pass
    state = getattr(
        getattr(jax, "_src", None), "distributed", None
    )
    state = getattr(state, "global_state", None)
    return state is not None and getattr(state, "client", None) is not None


def _enable_cpu_collectives() -> None:
    """Select the gloo collectives implementation for the CPU backend
    before it is instantiated: without it, current jax rejects every
    cross-process computation on CPU clusters ("Multiprocess
    computations aren't implemented on the CPU backend") — the local
    N-process dryrun/chaos legs and any gloo-backed CPU cluster need
    it. Only applies when the CPU platform was explicitly requested
    (``JAX_PLATFORMS=cpu`` / config), and quietly no-ops on jax
    versions without the option."""
    import os

    import jax

    platforms = (
        str(getattr(jax.config, "jax_platforms", None) or "")
        or os.environ.get("JAX_PLATFORMS", "")
    )
    if "cpu" not in platforms.lower():
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - option absent/renamed
        import logging

        logging.getLogger(__name__).debug(
            "jax_cpu_collectives_implementation unavailable; CPU "
            "cross-process collectives may be unsupported"
        )


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the JAX distributed runtime (idempotent).

    With no arguments, relies on the TPU environment's auto-detection
    (GCE metadata / megascale env), which is the normal path on Cloud TPU
    pods. No-op when already initialized or when running single-process.

    ``num_processes``/``process_id`` describe a MANUALLY-specified
    cluster and are meaningless without the coordinator every process
    rendezvouses at — passing them alone would silently fall into
    auto-detection with the explicit topology ignored, so that is a
    loud config error instead.
    """
    import jax

    if (
        num_processes is not None or process_id is not None
    ) and coordinator_address is None:
        raise ValueError(
            "num_processes/process_id were given without a "
            "coordinator_address: an explicit cluster topology needs "
            "the coordinator every process rendezvouses at (e.g. "
            "runtime.coordinator_address=10.0.0.1:8476). On TPU pods, "
            "pass NONE of the three and let auto-detection run."
        )
    if is_distributed_initialized():
        return  # Already initialized.
    if coordinator_address is not None:
        # Only when actually forming a cluster: gloo with NO
        # distributed client breaks single-process CPU backend init.
        _enable_cpu_collectives()
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except (ValueError, RuntimeError) as e:
        if coordinator_address is not None:
            raise
        # Auto-detection unavailable (single host, no cluster env): fine.
        import logging

        logging.getLogger(__name__).debug(
            "jax.distributed.initialize skipped: %s", e
        )


@component
class DistributedRuntime:
    """Component wrapper so pod bootstrap is configurable from the CLI::

        python train.py Exp runtime.coordinator_address=10.0.0.2:1234 \\
            runtime.num_processes=8 runtime.process_id=0
    """

    coordinator_address: Optional[str] = Field(None)
    num_processes: int = Field(-1)
    process_id: int = Field(-1)
    enabled: bool = Field(True)

    def initialize(self) -> None:
        if not self.enabled:
            return
        initialize_distributed(
            coordinator_address=self.coordinator_address,
            num_processes=None if self.num_processes < 0 else self.num_processes,
            process_id=None if self.process_id < 0 else self.process_id,
        )
