"""Multi-host (pod) runtime initialization.

The distributed communication backend (SURVEY.md §2.5): collectives are
XLA-compiled from sharding annotations and ride ICI within a pod slice and
DCN across slices — there is no hand-written NCCL/MPI-style layer, by
design. What remains host-side is bootstrapping the JAX distributed
runtime so all processes agree on topology, which this module owns, plus
small helpers for process-level facts the data pipeline needs.

Failure/recovery model (SURVEY.md §5): crash-restart with deterministic
resume — a failed pod job restarts, ``jax.distributed.initialize`` re-forms
the cluster, and the Experiment restores the latest orbax checkpoint; the
(seed, epoch)-keyed data pipeline makes the replay exact.
"""

from typing import Optional

from zookeeper_tpu.core import Field, component


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the JAX distributed runtime (idempotent).

    With no arguments, relies on the TPU environment's auto-detection
    (GCE metadata / megascale env), which is the normal path on Cloud TPU
    pods. No-op when already initialized or when running single-process.
    """
    import jax

    state = getattr(jax._src.distributed, "global_state", None)
    if state is not None and getattr(state, "client", None) is not None:
        return  # Already initialized.
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except (ValueError, RuntimeError) as e:
        if coordinator_address is not None:
            raise
        # Auto-detection unavailable (single host, no cluster env): fine.
        import logging

        logging.getLogger(__name__).debug(
            "jax.distributed.initialize skipped: %s", e
        )


@component
class DistributedRuntime:
    """Component wrapper so pod bootstrap is configurable from the CLI::

        python train.py Exp runtime.coordinator_address=10.0.0.2:1234 \\
            runtime.num_processes=8 runtime.process_id=0
    """

    coordinator_address: Optional[str] = Field(None)
    num_processes: int = Field(-1)
    process_id: int = Field(-1)
    enabled: bool = Field(True)

    def initialize(self) -> None:
        if not self.enabled:
            return
        initialize_distributed(
            coordinator_address=self.coordinator_address,
            num_processes=None if self.num_processes < 0 else self.num_processes,
            process_id=None if self.process_id < 0 else self.process_id,
        )
