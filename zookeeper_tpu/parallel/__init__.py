"""Distribution subsystem: device meshes, shardings, partitioners.

The TPU-native replacement for the reference GPU baseline's
``tf.distribute.MirroredStrategy`` + NCCL (SURVEY.md §2.5): a
``Partitioner`` component owns the ``jax.sharding.Mesh`` and the placement
of state and data; the training step itself stays a pure function and XLA
inserts all collectives (gradient all-reduce over ICI for data parallelism,
all-gathers for tensor-parallel params) from sharding annotations alone —
no hand-written communication layer, by design.
"""

from zookeeper_tpu.parallel.partitioner import (
    DataParallelPartitioner,
    FsdpPartitioner,
    MeshPartitioner,
    Partitioner,
    SingleDevicePartitioner,
)
from zookeeper_tpu.parallel.rules import (
    PartitionRule,
    auto_fsdp_rules,
    conv_model_tp_rules,
    match_partition_rules,
    transformer_tp_rules,
)
from zookeeper_tpu.parallel.sequence import SequenceParallelPartitioner
from zookeeper_tpu.parallel.distributed import (
    DistributedRuntime,
    initialize_distributed,
    is_distributed_initialized,
)
from zookeeper_tpu.parallel.sharding import (
    activation_sharding_scope,
    constrain_batch_sharded,
)

__all__ = [
    "activation_sharding_scope",
    "constrain_batch_sharded",
    "DataParallelPartitioner",
    "DistributedRuntime",
    "FsdpPartitioner",
    "auto_fsdp_rules",
    "MeshPartitioner",
    "Partitioner",
    "PartitionRule",
    "SequenceParallelPartitioner",
    "SingleDevicePartitioner",
    "conv_model_tp_rules",
    "initialize_distributed",
    "is_distributed_initialized",
    "match_partition_rules",
    "transformer_tp_rules",
]
