"""Model summary with quantization-aware size accounting.

The larq ``models.summary`` capability (SURVEY.md §1 ecosystem row),
TPU-native: per-parameter rows with train dtype vs deployment bit-width,
the packed deployment size (binary kernels ship 1 bit/weight — the 32x
compression the packed inference path actually realizes on device, see
``ops.packed``), and the model's forward FLOPs from XLA's own cost
analysis of the compiled apply (no hand-counted MACs to drift from the
real graph).

Everything is derived via ``jax.eval_shape`` — no parameters are
materialized, so summarizing an ImageNet-scale model is instant and
allocation-free.
"""

import re
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

__all__ = ["ModelSummary", "ParamRow", "model_summary"]

from zookeeper_tpu.ops.layers import BINARY_KERNEL_PATTERN

#: Latent kernels read through a sign quantizer: deployable at 1 bit.
_BINARY_KERNEL_PATTERN = re.compile(BINARY_KERNEL_PATTERN)
#: Already-packed deployment kernels (int32 lanes of 32 binary weights).
_PACKED_KERNEL_PATTERN = re.compile(r"kernel_packed$")


@dataclass
class ParamRow:
    path: str
    shape: Tuple[int, ...]
    dtype: str
    #: STORED elements (int32 lanes for pre-packed kernels).
    count: int
    #: Bits per weight in the packed deployment form.
    deploy_bits: int
    binary: bool
    #: True for kernel_packed rows: each stored int32 lane carries 32
    #: binary weights, so weight_count = 32 * count.
    packed: bool = False

    @property
    def weight_count(self) -> int:
        """Logical weights represented (what "params" means to a user)."""
        return self.count * 32 if self.packed else self.count

    @property
    def train_bytes(self) -> int:
        import jax.numpy as jnp

        return self.count * jnp.dtype(self.dtype).itemsize

    @property
    def deploy_bytes(self) -> float:
        return self.weight_count * self.deploy_bits / 8


@dataclass
class ModelSummary:
    rows: List[ParamRow]
    flops: Optional[float] = None  # Forward-pass FLOPs (XLA cost analysis).
    input_shape: Optional[Tuple[int, ...]] = None
    extra_collections: List[str] = field(default_factory=list)

    @property
    def total_params(self) -> int:
        return sum(r.weight_count for r in self.rows)

    @property
    def binary_params(self) -> int:
        return sum(r.weight_count for r in self.rows if r.binary)

    @property
    def fp_params(self) -> int:
        return self.total_params - self.binary_params

    @property
    def train_bytes(self) -> int:
        return sum(r.train_bytes for r in self.rows)

    @property
    def deploy_bytes(self) -> float:
        return sum(r.deploy_bytes for r in self.rows)

    def __str__(self) -> str:
        header = f"{'param':<58}{'shape':<20}{'dtype':<10}{'count':>12}{'bits':>6}"
        lines = [header, "-" * len(header)]
        for r in self.rows:
            shape = "x".join(str(s) for s in r.shape) or "scalar"
            lines.append(
                f"{r.path:<58}{shape:<20}{r.dtype:<10}{r.weight_count:>12,}"
                f"{r.deploy_bits:>6}"
            )
        lines.append("-" * len(header))
        lines.append(
            f"params: {self.total_params:,} "
            f"({self.binary_params:,} binary / {self.fp_params:,} fp)"
        )
        lines.append(
            f"memory: train {self.train_bytes / 2**20:.2f} MiB -> "
            f"deploy {self.deploy_bytes / 2**20:.2f} MiB "
            f"(binary kernels packed to 1 bit)"
        )
        if self.flops is not None:
            lines.append(f"forward FLOPs (XLA, batch 1): {self.flops:,.0f}")
        return "\n".join(lines)


def _classify(path: str, dtype_bits: int) -> Tuple[int, bool, bool]:
    """(deploy_bits, is_binary, is_packed) for one param path."""
    if _PACKED_KERNEL_PATTERN.search(path):
        # Already in deployment form: int32 lanes, 32 binary weights per
        # stored element (1 bit/weight). weight_count accounting restores
        # the true parameter count.
        return 1, True, True
    if _BINARY_KERNEL_PATTERN.search(path):
        return 1, True, False
    return dtype_bits, False, False


def model_summary(
    module: Any,
    input_shape: Sequence[int],
    *,
    compute_flops: bool = False,
    input_dtype: Any = None,
) -> ModelSummary:
    """Summarize a flax module's parameters and (optionally) FLOPs.

    ``compute_flops=True`` traces+lowers the forward apply and asks XLA's
    cost analysis for the FLOP count (compilation-free where supported;
    falls back to ``None`` silently since it is diagnostic output).

    ``input_dtype``: the dummy input's dtype. Callers that know the
    pipeline should pass it explicitly — the data layer's
    ``Preprocessing.input_dtype`` hint is the canonical source
    (``TokenPreprocessing`` -> int32, image preprocessing -> float32;
    the experiment's ``print_model_summary`` threads it through).
    When None, the default keys off the MODEL FAMILY, not the input
    rank (ADVICE summary.py:50): a module that declares a
    ``vocab_size`` — the token-pipeline marker every embedding-fronted
    LM here carries (``TransformerLMModule``) — gets an int32 dummy (a
    float dummy is an invalid embedding index), everything else gets
    float32, so a rank-1 FLOAT-feature model (an MLP over flat
    features) traces with the right dtype without needing the hint.
    """
    import jax
    import jax.numpy as jnp
    from flax import traverse_util

    if input_dtype is None:
        input_dtype = (
            jnp.int32
            if isinstance(getattr(module, "vocab_size", None), int)
            else jnp.float32
        )
    x = jnp.zeros((1, *input_shape), input_dtype)
    variables = jax.eval_shape(
        lambda: module.init(jax.random.key(0), x, training=False)
    )
    params = variables.get("params", {})
    extra = sorted(k for k in variables if k != "params")

    rows = []
    for path, leaf in sorted(
        traverse_util.flatten_dict(params, sep="/").items()
    ):
        dtype = jnp.dtype(leaf.dtype)
        deploy_bits, binary, packed = _classify(path, dtype.itemsize * 8)
        rows.append(
            ParamRow(
                path=path,
                shape=tuple(leaf.shape),
                dtype=dtype.name,
                count=int(leaf.size),
                deploy_bits=deploy_bits,
                binary=binary,
                packed=packed,
            )
        )

    flops = None
    if compute_flops:
        try:
            from zookeeper_tpu.observability.ledger import default_ledger

            # Lower from the abstract eval_shape tree directly — no
            # parameter materialization even at ImageNet scale. FLOPs
            # extraction goes through the ONE shared cost_analysis
            # wrapper (docs/DESIGN.md §14) — the same helper the
            # program ledger, the serving engine, and bench.py use, so
            # backend quirks (None / [dict] / missing keys) are
            # handled in exactly one place.
            lowered = jax.jit(
                lambda v, x: module.apply(v, x, training=False)
            ).lower(variables, x)
            flops = default_ledger().record(
                "summary_forward",
                f"{type(module).__name__}/b1x"
                + "x".join(str(s) for s in input_shape),
                lowered=lowered,
            ).flops
        except Exception:
            flops = None

    return ModelSummary(
        rows=rows,
        flops=flops,
        input_shape=tuple(input_shape),
        extra_collections=extra,
    )
