"""Model components.

Capability parity with the reference's ``zookeeper/tf/model.py``
(SURVEY.md §2.2): an abstract ``Model`` component whose ``build(...)``
returns the framework-native network object — here a ``flax.linen.Module``
instead of a ``tf.keras.Model``. Architectures (the larq-zoo-equivalent
families) live in submodules and register themselves as ``Model``
subclasses for subclass-by-name configuration (``model=QuickNet``).
"""

from zookeeper_tpu.models.base import Model
from zookeeper_tpu.models.keras_import import (
    import_keras_weights,
    keras_transpose_kernel,
)
from zookeeper_tpu.models.simple import Mlp, SimpleCnn
from zookeeper_tpu.models.binary import (
    BinaryAlexNet,
    BinaryDenseNet28,
    BinaryDenseNet37,
    BinaryDenseNet37Dilated,
    BinaryDenseNet45,
    BinaryNet,
    BinaryResNetE18,
    BiRealNet,
    DoReFaNet,
    MeliusNet22,
    QuickNet,
    QuickNetLarge,
    QuickNetSmall,
    RealToBinaryNet,
    ReActNet,
    XNORNet,
)
from zookeeper_tpu.models.resnet import ResNet50, ResNet101, ResNet152
from zookeeper_tpu.models.transformer import (
    TransformerLM,
    TransformerLMModule,
    greedy_decode,
)
from zookeeper_tpu.models.summary import ModelSummary, model_summary

__all__ = [
    "import_keras_weights",
    "keras_transpose_kernel",
    "ModelSummary",
    "greedy_decode",
    "model_summary",
    "BinaryAlexNet",
    "BinaryDenseNet28",
    "BinaryDenseNet37",
    "BinaryDenseNet37Dilated",
    "BinaryDenseNet45",
    "BinaryNet",
    "BinaryResNetE18",
    "BiRealNet",
    "DoReFaNet",
    "MeliusNet22",
    "Mlp",
    "TransformerLM",
    "TransformerLMModule",
    "Model",
    "QuickNet",
    "QuickNetLarge",
    "QuickNetSmall",
    "ReActNet",
    "RealToBinaryNet",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "SimpleCnn",
    "XNORNet",
]
