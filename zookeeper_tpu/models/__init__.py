"""Model components.

Capability parity with the reference's ``zookeeper/tf/model.py``
(SURVEY.md §2.2): an abstract ``Model`` component whose ``build(...)``
returns the framework-native network object — here a ``flax.linen.Module``
instead of a ``tf.keras.Model``. Architectures (the larq-zoo-equivalent
families) live in submodules and register themselves as ``Model``
subclasses for subclass-by-name configuration (``model=QuickNet``).
"""

from zookeeper_tpu.models.base import Model
from zookeeper_tpu.models.simple import Mlp, SimpleCnn

__all__ = ["Model", "Mlp", "SimpleCnn"]


def _register_zoo() -> None:
    """Import zoo submodules for their registration side effects (subclass
    trees must be populated before subclass-by-name lookup)."""
    from zookeeper_tpu.models import binary, resnet  # noqa: F401


try:  # Zoo families require the quant ops; keep base importable regardless.
    _register_zoo()
except ImportError:  # pragma: no cover
    pass
