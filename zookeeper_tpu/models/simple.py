"""Small reference architectures (MNIST/CIFAR scale).

The TPU-native counterpart of the reference example's small Keras CNN
(SURVEY.md §2.3 `examples/larq_experiment.py` [unverified]): enough model
to prove the whole component contract drives a real JAX training loop
(BASELINE config #1).
"""

from typing import Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.models.base import Model
from zookeeper_tpu.ops.layers import BatchNorm


class _CnnModule(nn.Module):
    features: Tuple[int, ...]
    dense_units: Tuple[int, ...]
    num_classes: int
    use_batch_norm: bool
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = x.astype(self.dtype)
        for i, f in enumerate(self.features):
            x = nn.Conv(f, (3, 3), padding="SAME", dtype=self.dtype)(x)
            if self.use_batch_norm:
                x = BatchNorm(use_running_average=not training)(x)
            x = nn.relu(x)
            if i % 2 == 1:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        for u in self.dense_units:
            x = nn.Dense(u, dtype=self.dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class _MlpModule(nn.Module):
    hidden_units: Tuple[int, ...]
    num_classes: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        for u in self.hidden_units:
            x = nn.relu(nn.Dense(u, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=self.dtype)(x).astype(jnp.float32)


@component
class SimpleCnn(Model):
    """Small conv net: [conv(-bn)-relu]xN with pooling, dense head."""

    features: Sequence[int] = Field((32, 64))
    dense_units: Sequence[int] = Field((128,))
    use_batch_norm: bool = Field(True)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _CnnModule(
            features=tuple(self.features),
            dense_units=tuple(self.dense_units),
            num_classes=num_classes,
            use_batch_norm=self.use_batch_norm,
            dtype=self.dtype(),
        )


@component
class Mlp(Model):
    """Flatten + dense stack, the minimal smoke-test model."""

    hidden_units: Sequence[int] = Field((128,))

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _MlpModule(
            hidden_units=tuple(self.hidden_units),
            num_classes=num_classes,
            dtype=self.dtype(),
        )
