"""Binarized model zoo (larq-zoo-equivalent families).

TPU-native reconstructions of the workload ecosystem's binary
architectures (SURVEY.md §2.4/§6: BinaryNet, BinaryAlexNet, Bi-Real-Net,
QuickNet). Built from first principles against the published papers —
NOT ports of larq_zoo code; block counts/widths follow the papers and the
BASELINE.md accuracy table, with deviations noted per class.

Common recipe: latent fp32 weights, ``ste_sign``-family quantizers with
weight clipping, BatchNorm after every binary conv (binary nets are
BN-hungry), first/last layers full-precision (standard practice — they
carry too much information to binarize).
"""

from functools import partial
from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.models.base import Model
from zookeeper_tpu.ops.layers import BatchNorm, QuantConv, QuantDense
from zookeeper_tpu.ops.quantizers import dorefa, ste_sign


_FOLD_BN_TRAINING_ERROR = (
    "fold_bn=True is a DEPLOYMENT mode: the binary-conv BatchNorms are "
    "folded into conv params at convert time and skipped here, so a "
    "training=True apply would run un-normalized with batch stats "
    "silently missing. Train with fold_bn=False and convert with "
    "pack_quantconv_params(fold_bn=True)."
)


def _bn(training: bool, dtype=jnp.float32):
    # ops.layers.BatchNorm == nn.BatchNorm + batch-dim sharding pin.
    return BatchNorm(
        use_running_average=not training, momentum=0.9, epsilon=1e-5,
        dtype=dtype,
    )


def _check_fold_training(fold_bn, packed_weights, training: bool) -> None:
    """Loud guard for the fold_bn deployment mode: raise on a training
    apply of a build that actually folds (fold applies only where the
    layer is PACKED, so an unpacked build with a config-inherited
    fold_bn=True trains normally). ``packed_weights`` may be a
    per-section tuple."""
    packed_any = (
        any(packed_weights)
        if isinstance(packed_weights, (tuple, list))
        else bool(packed_weights)
    )
    if fold_bn and packed_any and training:
        raise ValueError(_FOLD_BN_TRAINING_ERROR)


def _dense_stage_fold(
    fold_bn: bool,
    conv_packed: bool,
    dense_packed,
    training: bool,
    family: str,
    pooled_convs: str,
) -> bool:
    """Resolve the dense-stage fold flag for the VGG-style families
    (BinaryNet, BinaryAlexNet): their convs feed a maxpool BEFORE the
    BatchNorm, and max only commutes with the folded per-channel affine
    when the BN scale is positive — a conv fold would be silently wrong
    for learned negative scales, so conv-packed + fold raises. Returns
    whether the dense stage folds."""
    if fold_bn and conv_packed:
        raise ValueError(
            f"{family} fold_bn supports the DENSE stage only: "
            f"{pooled_convs} feed a maxpool before their BatchNorm, and "
            "max only commutes with the folded affine when the BN scale "
            "is positive. Pack/fold the dense stage "
            "(dense_packed_weights=True) and keep packed_weights=False."
        )
    _check_fold_training(fold_bn, bool(dense_packed), training)
    return fold_bn and bool(dense_packed)


def _post_conv_bn(y, training: bool, dtype, fold_here: bool):
    """The BN after a binary conv — or, in fold mode, its SKIP: the BN
    module is constructed either way so flax auto-numbering matches the
    trained checkpoint, but a folded conv's epilogue (kernel_scale/bias
    rewritten by pack_quantconv_params) already carries the affine."""
    bn = _bn(training, dtype)
    return y if fold_here else bn(y)


class _BinaryNetModule(nn.Module):
    """VGG-style BinaryNet (Courbariaux et al. 2016): the reference
    example's CIFAR/MNIST capability (SURVEY.md §2.3)."""

    features: Tuple[int, ...]
    dense_units: Tuple[int, ...]
    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    #: None = follow binary_compute / packed_weights (see BinaryAlexNet).
    dense_binary_compute: Optional[str] = None
    dense_packed_weights: Optional[bool] = None
    #: Deployment-only, DENSE stage only: odd-indexed convs feed a
    #: maxpool before their BN (fold-invalid for negative BN scales —
    #: see _BinaryAlexNetModule.fold_bn), so conv-packed + fold raises.
    fold_bn: bool = False
    pallas_interpret: bool = False
    #: §21 kernel flavor for the binary layers (see QuantConv).
    binary_flavor: str = "auto"

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = x.astype(self.dtype)
        for i, f in enumerate(self.features):
            # First conv: fp input (standard for binary nets) — it cannot
            # run a binary compute path, so it stays on mxu explicitly.
            quant_in = None if i == 0 else "ste_sign"
            x = QuantConv(
                f, (3, 3), input_quantizer=quant_in,
                kernel_quantizer="ste_sign", dtype=self.dtype,
                binary_compute="mxu" if i == 0 else self.binary_compute,
                packed_weights=False if i == 0 else self.packed_weights,
                pallas_interpret=self.pallas_interpret,
                binary_flavor=self.binary_flavor,
            )(x)
            if i % 2 == 1:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x = _bn(training, self.dtype)(x)
        x = x.reshape((x.shape[0], -1))
        dense_bc = (
            self.binary_compute
            if self.dense_binary_compute is None
            else self.dense_binary_compute
        )
        dense_packed = (
            self.packed_weights
            if self.dense_packed_weights is None
            else self.dense_packed_weights
        )
        dense_fold = _dense_stage_fold(
            self.fold_bn, bool(self.packed_weights), dense_packed,
            training, "BinaryNet", "odd-indexed convs",
        )
        for u in self.dense_units:
            x = QuantDense(
                u, input_quantizer="ste_sign", kernel_quantizer="ste_sign",
                use_bias=dense_fold, dtype=self.dtype,
                binary_compute=dense_bc,
                packed_weights=dense_packed,
                pallas_interpret=self.pallas_interpret,
                binary_flavor=self.binary_flavor,
            )(x)
            x = _post_conv_bn(x, training, self.dtype, dense_fold)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


@component
class BinaryNet(Model):
    """BinaryNet VGG for CIFAR-scale inputs."""

    features: Sequence[int] = Field((128, 128, 256, 256, 512, 512))
    dense_units: Sequence[int] = Field((1024, 1024))
    #: Binary conv path: "mxu", "int8", "xnor", or "xnor_popcount"
    #: (see QuantConv).
    binary_compute: str = Field("mxu")
    #: Inference-only: params are the bit-packed kernels (32x smaller);
    #: fill from a float checkpoint with ops.packed.pack_quantconv_params.
    packed_weights: bool = Field(False)
    #: Dense-stage overrides; unset = follow the conv-stage settings
    #: (see BinaryAlexNet).
    dense_binary_compute: str = Field(allow_missing=True)
    dense_packed_weights: bool = Field(allow_missing=True)
    #: Deployment-only, DENSE stage only (see _BinaryNetModule).
    fold_bn: bool = Field(False)
    #: Run Pallas kernels interpreted (CPU tests).
    pallas_interpret: bool = Field(False)
    #: §21 kernel flavor for the binary layers (see QuantConv).
    binary_flavor: str = Field("auto")

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _BinaryNetModule(
            features=tuple(self.features),
            dense_units=tuple(self.dense_units),
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            dense_binary_compute=getattr(self, "dense_binary_compute", None),
            dense_packed_weights=getattr(self, "dense_packed_weights", None),
            fold_bn=self.fold_bn,
            pallas_interpret=self.pallas_interpret,
            binary_flavor=self.binary_flavor,
        )


class _BinaryAlexNetModule(nn.Module):
    """Binary AlexNet (larq-zoo capability row; ~36.3% top-1 target)."""

    num_classes: int
    dtype: Any
    inflation: int = 1
    binary_compute: str = "mxu"
    packed_weights: bool = False
    #: None = follow binary_compute / packed_weights. The dense layers
    #: hold ~80% of the params AND run at M = batch (HBM-bound at small
    #: batch), so dense-only packing is the deployment sweet spot
    #: (BASELINE.md round-4 measurement).
    dense_binary_compute: Optional[str] = None
    dense_packed_weights: Optional[bool] = None
    #: Deployment-only: fold the BNs after the packed DENSE layers into
    #: their params (ops.packed fold_bn). Dense-stage only by
    #: construction: two of the four binary convs feed a maxpool BEFORE
    #: their BN, and a per-channel affine only commutes with max when
    #: its scale is positive — BN's learned scale can be negative, so a
    #: conv fold here would be silently wrong; conv-packed + fold_bn
    #: raises instead.
    fold_bn: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        f = self.inflation
        # Conv1: full precision (standard for binary nets).
        x = nn.Conv(64 * f, (11, 11), strides=(4, 4), padding="SAME",
                    use_bias=False, dtype=d)(x.astype(d))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = _bn(training, self.dtype)(x)
        for feat, k in ((192 * f, 5), (384 * f, 3), (384 * f, 3), (256 * f, 3)):
            x = QuantConv(
                feat, (k, k), input_quantizer="ste_sign",
                kernel_quantizer="ste_sign", dtype=d,
                binary_compute=self.binary_compute,
                packed_weights=self.packed_weights,
                pallas_interpret=self.pallas_interpret,
            )(x)
            if feat in (192 * f, 256 * f):
                x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
            x = _bn(training, self.dtype)(x)
        x = x.reshape((x.shape[0], -1))
        dense_bc = (
            self.binary_compute
            if self.dense_binary_compute is None
            else self.dense_binary_compute
        )
        dense_packed = (
            self.packed_weights
            if self.dense_packed_weights is None
            else self.dense_packed_weights
        )
        dense_fold = _dense_stage_fold(
            self.fold_bn, bool(self.packed_weights), dense_packed,
            training, "BinaryAlexNet", "two of the four binary convs",
        )
        for u in (4096, 4096):
            # The binary dense layers dominate BinaryAlexNet's parameter
            # count — the packed deployment's biggest 32x win.
            x = QuantDense(
                u, input_quantizer="ste_sign", kernel_quantizer="ste_sign",
                use_bias=dense_fold, dtype=d,
                binary_compute=dense_bc,
                packed_weights=dense_packed,
                pallas_interpret=self.pallas_interpret,
            )(x)
            x = _post_conv_bn(x, training, self.dtype, dense_fold)
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class BinaryAlexNet(Model):
    """Binarized AlexNet for ImageNet (BASELINE config #2)."""

    inflation: int = Field(1)
    binary_compute: str = Field("mxu")
    packed_weights: bool = Field(False)
    #: Dense-stage overrides ("" / -1 sentinel unsupported in str/bool
    #: Fields, so these are separate optional component fields):
    #: allow_missing = follow the conv-stage settings. Dense-only packing
    #: ("xnor" + True here, mxu convs) is the measured deployment sweet
    #: spot (BASELINE.md).
    dense_binary_compute: str = Field(allow_missing=True)
    dense_packed_weights: bool = Field(allow_missing=True)
    #: Deployment-only, DENSE stage only (see _BinaryAlexNetModule):
    #: pair with ops.packed.pack_quantconv_params fold_bn=True.
    fold_bn: bool = Field(False)
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        # allow_missing fields raise AttributeError on read; getattr's
        # default maps that to "follow the conv-stage settings".
        dense_bc = getattr(self, "dense_binary_compute", None)
        dense_packed = getattr(self, "dense_packed_weights", None)
        return _BinaryAlexNetModule(
            num_classes=num_classes, dtype=self.dtype(),
            inflation=self.inflation,
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            dense_binary_compute=dense_bc,
            dense_packed_weights=dense_packed,
            fold_bn=self.fold_bn,
            pallas_interpret=self.pallas_interpret,
        )


class _BiRealBlock(nn.Module):
    """One Bi-Real-Net block: sign -> binary 3x3 conv -> BN -> + identity.

    The real-valued shortcut after EVERY binary conv is the signature of
    Bi-Real-Net (Liu et al. 2018); activations use approx_sign, weights
    magnitude_aware_sign.
    """

    features: int
    strides: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    #: Deployment-only: the conv's following BN is folded into the conv
    #: params at convert time and skipped here (the shortcut BN stays —
    #: it follows an fp conv the fold pass never touches). Like
    #: QuickNet, folding applies only where the conv is PACKED — the
    #: converter emits folded scale/bias into the packed param structure
    #: only, and the gate also keeps a config-inherited fold_bn=True
    #: harmless on an unpacked (float/training) build.
    fold_bn: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        fold_here = self.fold_bn and self.packed_weights
        shortcut = x
        if self.strides > 1 or x.shape[-1] != self.features:
            # Real-valued downsample shortcut: avgpool + fp 1x1 conv + BN.
            shortcut = nn.avg_pool(
                x, (2, 2), strides=(self.strides, self.strides), padding="SAME"
            )
            shortcut = nn.Conv(
                self.features, (1, 1), use_bias=False, dtype=self.dtype
            )(shortcut)
            shortcut = _bn(training, self.dtype)(shortcut)
        y = QuantConv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            input_quantizer="approx_sign",
            kernel_quantizer="magnitude_aware_sign", dtype=self.dtype,
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            use_bias=fold_here,  # Carries the folded BN shift.
            pallas_interpret=self.pallas_interpret,
        )(x)
        y = _post_conv_bn(y, training, self.dtype, fold_here)
        return y + shortcut


class _BiRealNetModule(nn.Module):
    """Bi-Real-Net-18: 7x7 fp stem, 4 sections of binary blocks."""

    blocks_per_section: Tuple[int, ...]
    section_features: Tuple[int, ...]
    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    fold_bn: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        _check_fold_training(self.fold_bn, self.packed_weights, training)
        d = self.dtype
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, self.dtype)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for s, (n, feat) in enumerate(
            zip(self.blocks_per_section, self.section_features)
        ):
            for b in range(n):
                strides = 2 if (b == 0 and s > 0) else 1
                x = _BiRealBlock(
                    feat, strides, d, self.binary_compute,
                    self.packed_weights, fold_bn=self.fold_bn,
                    pallas_interpret=self.pallas_interpret,
                )(x, training)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class BiRealNet(Model):
    """Bi-Real-Net-18 (BASELINE config #3; ~56-57.5% top-1 target)."""

    blocks_per_section: Sequence[int] = Field((4, 4, 4, 4))
    section_features: Sequence[int] = Field((64, 128, 256, 512))
    binary_compute: str = Field("mxu")
    packed_weights: bool = Field(False)
    #: Deployment-only: binary-conv BNs folded into the conv epilogue
    #: (pair with ops.packed.pack_quantconv_params fold_bn=True).
    fold_bn: bool = Field(False)
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _BiRealNetModule(
            blocks_per_section=tuple(self.blocks_per_section),
            section_features=tuple(self.section_features),
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            fold_bn=self.fold_bn,
            pallas_interpret=self.pallas_interpret,
        )


def _blur_pool(x: jax.Array, dtype) -> jax.Array:
    """Anti-aliased stride-2 downsampling (Zhang 2019), used by QuickNet
    transitions: fixed 3x3 binomial filter, depthwise, stride 2."""
    c = x.shape[-1]
    f = jnp.array([1.0, 2.0, 1.0], dtype)
    k2d = jnp.outer(f, f)
    k2d = k2d / k2d.sum()
    kernel = jnp.tile(k2d[:, :, None, None], (1, 1, 1, c))  # HWIO, I=1 (dw)
    return jax.lax.conv_general_dilated(
        x, kernel, window_strides=(2, 2), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )


class _QuickNetModule(nn.Module):
    """QuickNet family (Bannink et al. 2021, "Larq Compute Engine" /
    larq-zoo sota): fp stem, sections of residual binary 3x3 convs, fp
    pointwise transition with blurpool downsampling.

    ``binary_compute``/``packed_weights`` may be PER-SECTION tuples for
    mixed deployment: the packed path wins only where M (spatial
    positions) is small and K large — the deep sections, which also hold
    ~95% of the binary weights (BASELINE.md) — so e.g.
    ``binary_compute=("int8","int8","xnor","xnor")`` with
    ``packed_weights=(False,False,True,True)`` keeps early sections on
    the fast plain-MXU path while the deep sections ship bit-packed.

    Reconstruction from the paper's description; exact stem/transition
    minutiae may deviate from larq_zoo (documented deviation, SURVEY.md §6
    accuracies are approximate targets).
    """

    blocks_per_section: Tuple[int, ...]
    section_features: Tuple[int, ...]
    num_classes: int
    dtype: Any
    binary_compute: Any = "mxu"  # str | per-section tuple of str
    packed_weights: Any = False  # bool | per-section tuple of bool
    #: 1-bit fwd->bwd residual storage on the binary convs (requires the
    #: int8 path; see QuantConv.pack_residuals).
    pack_residuals: bool = False
    #: DEPLOYMENT-ONLY: skip the BatchNorm after each binary conv — its
    #: eval-mode scale/shift is folded into the conv's kernel_scale and
    #: a bias at convert time (ops.packed.pack_quantconv_params
    #: fold_bn=True), erasing four fp32 vectors per conv from the
    #: deployed params. The uncalled BN is still CONSTRUCTED so flax
    #: auto-numbering of the remaining (stem/transition) BatchNorms
    #: matches the trained checkpoint. Invalid for training (batch-stats
    #: BN cannot fold).
    fold_bn: bool = False
    pallas_interpret: bool = False
    #: §21 kernel flavor for the binary convs ("auto"/"pallas"/
    #: "reference"; numerics-identical — see QuantConv.binary_flavor).
    binary_flavor: str = "auto"

    def _section_opt(self, value, s: int):
        if isinstance(value, (tuple, list)):
            return value[s]
        return value

    @nn.compact
    def __call__(self, x, training: bool = False):
        _check_fold_training(self.fold_bn, self.packed_weights, training)
        d = self.dtype
        # The fp stem/transition segments pin activations to the
        # canonical dp x tp layout like the Quant* layers do
        # (parallel/sharding.py). Without the pins the segments are
        # GSPMD-free territory, and at data-axis sizes > 4 the
        # propagator was observed choosing a batch-over-all-axes layout
        # for the grouped stem conv / blurpool that it could only leave
        # by involuntary full rematerialization (found by the 16-device
        # dryrun leg; value-identical either way — pins are layout-only
        # and no-ops outside a partitioner scope).
        from zookeeper_tpu.parallel.sharding import constrain_batch_sharded

        # Stem: fp 3x3/2 to 8ch, then grouped 3x3/2 to first section width.
        x = nn.Conv(8, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, self.dtype)(x)
        x = constrain_batch_sharded(nn.relu(x))
        x = nn.Conv(
            self.section_features[0], (3, 3), strides=(2, 2), padding="SAME",
            use_bias=False, feature_group_count=4, dtype=d,
        )(x)
        x = constrain_batch_sharded(_bn(training, self.dtype)(x))
        for s, (n, feat) in enumerate(
            zip(self.blocks_per_section, self.section_features)
        ):
            if s > 0:
                # Transition: blurpool downsample + fp 1x1 conv to widen.
                x = constrain_batch_sharded(nn.relu(x))
                x = constrain_batch_sharded(_blur_pool(x, d))
                x = nn.Conv(feat, (1, 1), use_bias=False, dtype=d)(x)
                x = constrain_batch_sharded(_bn(training, self.dtype)(x))
            for _ in range(n):
                # BN folds only where the section ships packed (the
                # converter emits the folded scale/bias into the packed
                # param structure); unpacked sections keep their BN.
                fold_here = self.fold_bn and bool(
                    self._section_opt(self.packed_weights, s)
                )
                y = QuantConv(
                    feat, (3, 3), input_quantizer="ste_sign",
                    kernel_quantizer="ste_sign", dtype=d,
                    binary_compute=self._section_opt(self.binary_compute, s),
                    packed_weights=self._section_opt(self.packed_weights, s),
                    pack_residuals=self.pack_residuals,
                    use_bias=fold_here,  # Carries the folded BN shift.
                    pallas_interpret=self.pallas_interpret,
                    binary_flavor=self.binary_flavor,
                )(x)
                y = _post_conv_bn(y, training, d, fold_here)
                x = x + y  # Residual around every binary conv.
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class QuickNet(Model):
    """QuickNet (~63.3% top-1 target; BASELINE configs #4).

    ``binary_compute``/``packed_weights`` accept a single value or a
    per-section tuple (mixed deployment — see _QuickNetModule)."""

    blocks_per_section: Sequence[int] = Field((2, 3, 4, 4))
    section_features: Sequence[int] = Field((64, 128, 256, 512))
    binary_compute: Union[str, Sequence[str]] = Field("mxu")
    packed_weights: Union[bool, Sequence[bool]] = Field(False)
    #: 1-bit residual storage on the binary convs (int8 path only).
    pack_residuals: bool = Field(False)
    #: Deployment-only: binary-conv BNs folded into the conv epilogue
    #: (pair with ops.packed.pack_quantconv_params fold_bn=True).
    fold_bn: bool = Field(False)
    pallas_interpret: bool = Field(False)
    #: §21 kernel flavor for the binary convs (see QuantConv).
    binary_flavor: str = Field("auto")

    def build(self, input_shape, num_classes: int) -> nn.Module:
        n_sections = len(tuple(self.blocks_per_section))

        def norm(v):
            if isinstance(v, (list, tuple)):
                if len(v) != n_sections:
                    raise ValueError(
                        f"Per-section value {tuple(v)!r} has {len(v)} "
                        f"entries but the model has {n_sections} sections "
                        "(one entry per blocks_per_section section)."
                    )
                return tuple(v)
            return v

        return _QuickNetModule(
            blocks_per_section=tuple(self.blocks_per_section),
            section_features=tuple(self.section_features),
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=norm(self.binary_compute),
            packed_weights=norm(self.packed_weights),
            pack_residuals=self.pack_residuals,
            fold_bn=self.fold_bn,
            pallas_interpret=self.pallas_interpret,
            binary_flavor=self.binary_flavor,
        )


@component
class QuickNetSmall(QuickNet):
    section_features: Sequence[int] = Field((32, 64, 256, 512))


@component
class QuickNetLarge(QuickNet):
    """QuickNet-Large (~66.9% top-1 target; the north-star workload)."""

    blocks_per_section: Sequence[int] = Field((6, 8, 12, 6))


class _ResNetEBlock(nn.Module):
    """BinaryResNetE block (Bethge et al. 2019, "Back to Simplicity"):
    sign -> binary 3x3 conv -> BN -> + shortcut, where the downsample
    shortcut is PARAMETER-FREE: 2x2 average pool + channel duplication
    (concat), keeping the skip path fully real-valued without fp convs.
    """

    features: int
    strides: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    #: Deployment-only: the conv's following BN is folded into the conv
    #: params at convert time and skipped here (only where PACKED — see
    #: _BiRealBlock.fold_bn).
    fold_bn: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        shortcut = x
        if self.strides > 1:
            shortcut = nn.avg_pool(
                x, (2, 2), strides=(self.strides, self.strides), padding="SAME"
            )
        if shortcut.shape[-1] != self.features:
            assert self.features % shortcut.shape[-1] == 0
            reps = self.features // shortcut.shape[-1]
            shortcut = jnp.concatenate([shortcut] * reps, axis=-1)
        fold_here = self.fold_bn and self.packed_weights
        y = QuantConv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            input_quantizer="ste_sign", kernel_quantizer="ste_sign",
            dtype=self.dtype, binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            use_bias=fold_here,  # Carries the folded BN shift.
            pallas_interpret=self.pallas_interpret,
        )(x)
        y = _post_conv_bn(y, training, self.dtype, fold_here)
        return y + shortcut


class _BinaryResNetEModule(nn.Module):
    """BinaryResNetE18: 7x7 fp stem, 4 sections of ResNetE blocks."""

    blocks_per_section: Tuple[int, ...]
    section_features: Tuple[int, ...]
    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    fold_bn: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        _check_fold_training(self.fold_bn, self.packed_weights, training)
        d = self.dtype
        x = nn.Conv(self.section_features[0], (7, 7), strides=(2, 2),
                    padding="SAME", use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, d)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for s, (n, feat) in enumerate(
            zip(self.blocks_per_section, self.section_features)
        ):
            for b in range(n):
                strides = 2 if (b == 0 and s > 0) else 1
                x = _ResNetEBlock(
                    feat, strides, d, self.binary_compute,
                    self.packed_weights, fold_bn=self.fold_bn,
                    pallas_interpret=self.pallas_interpret,
                )(x, training)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class BinaryResNetE18(Model):
    """BinaryResNetE18 (larq-zoo literature family; ~58% top-1 target).

    Distinguishing feature vs Bi-Real-Net: parameter-free downsample
    shortcuts (avgpool + channel duplication) and plain ste_sign on both
    activations and weights.
    """

    blocks_per_section: Sequence[int] = Field((4, 4, 4, 4))
    section_features: Sequence[int] = Field((64, 128, 256, 512))
    binary_compute: str = Field("mxu")
    packed_weights: bool = Field(False)
    #: Deployment-only: binary-conv BNs folded into the conv epilogue
    #: (pair with ops.packed.pack_quantconv_params fold_bn=True).
    fold_bn: bool = Field(False)
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _BinaryResNetEModule(
            blocks_per_section=tuple(self.blocks_per_section),
            section_features=tuple(self.section_features),
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            fold_bn=self.fold_bn,
            pallas_interpret=self.pallas_interpret,
        )


def _round_channels(c: float, multiple: int = 32) -> int:
    return max(multiple, int(c / multiple + 0.5) * multiple)


class _BinaryDenseNetModule(nn.Module):
    """BinaryDenseNet (Bethge et al. 2019): dense blocks of binary 3x3
    convs whose outputs CONCATENATE onto the feature stack (growth), with
    full-precision 1x1 reduction convs at block transitions.

    Dense connectivity sidesteps the information bottleneck of binary
    residual adds: every layer sees all earlier feature maps at full
    value resolution. Transitions follow the paper: BN -> relu ->
    (maxpool if downsampling) -> fp 1x1 conv with reduction rate;
    reduced widths are rounded to multiples of 32 (documented deviation
    — keeps every GEMM MXU-tile-aligned).
    """

    layers_per_block: Tuple[int, ...]
    reduction: Tuple[float, ...]
    dilation: Tuple[int, ...]
    growth_rate: int
    initial_features: int
    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        x = nn.Conv(self.initial_features, (7, 7), strides=(2, 2),
                    padding="SAME", use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, d)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for block, n_layers in enumerate(self.layers_per_block):
            dil = self.dilation[block]
            for _ in range(n_layers):
                y = _bn(training, d)(x)
                y = QuantConv(
                    self.growth_rate, (3, 3),
                    kernel_dilation=(dil, dil),
                    input_quantizer="ste_sign", kernel_quantizer="ste_sign",
                    dtype=d, binary_compute=self.binary_compute,
                    packed_weights=self.packed_weights,
                    pallas_interpret=self.pallas_interpret,
                )(y)
                x = jnp.concatenate([x, y], axis=-1)
            if block < len(self.layers_per_block) - 1:
                x = _bn(training, d)(x)
                x = nn.relu(x)
                if self.dilation[block + 1] == 1:
                    x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
                x = nn.Conv(
                    _round_channels(x.shape[-1] / self.reduction[block]),
                    (1, 1), use_bias=False, dtype=d,
                )(x)
        x = _bn(training, d)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class BinaryDenseNet28(Model):
    """BinaryDenseNet-28 (~60.7% top-1 target)."""

    layers_per_block: Sequence[int] = Field((6, 6, 6, 5))
    reduction: Sequence[float] = Field((2.7, 2.7, 2.2))
    #: Per-block conv dilation; blocks with dilation > 1 skip the
    #: transition downsample (the dilated variants trade stride for
    #: receptive field).
    dilation: Sequence[int] = Field((1, 1, 1, 1))
    growth_rate: int = Field(64)
    initial_features: int = Field(64)
    binary_compute: str = Field("mxu")
    packed_weights: bool = Field(False)
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _BinaryDenseNetModule(
            layers_per_block=tuple(self.layers_per_block),
            reduction=tuple(self.reduction),
            dilation=tuple(self.dilation),
            growth_rate=self.growth_rate,
            initial_features=self.initial_features,
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            pallas_interpret=self.pallas_interpret,
        )


@component
class BinaryDenseNet37(BinaryDenseNet28):
    """BinaryDenseNet-37 (~62.5% top-1 target)."""

    layers_per_block: Sequence[int] = Field((6, 8, 12, 6))
    reduction: Sequence[float] = Field((3.3, 3.3, 4.0))


@component
class BinaryDenseNet37Dilated(BinaryDenseNet37):
    """BinaryDenseNet-37 with dilated (stride-free) last two stages
    (~63.7% top-1 target; more FLOPs at higher resolution)."""

    dilation: Sequence[int] = Field((1, 1, 2, 4))


@component
class BinaryDenseNet45(BinaryDenseNet28):
    """BinaryDenseNet-45 (~63.7% top-1 target)."""

    layers_per_block: Sequence[int] = Field((6, 12, 14, 8))
    reduction: Sequence[float] = Field((2.7, 3.3, 4.0))


class _XnorNetModule(nn.Module):
    """XNOR-Net (Rastegari et al. 2016): binarized AlexNet where binary
    weights carry a per-output-filter fp scale alpha = mean|W| (exactly
    the magnitude_aware_sign quantizer's scaling) and the layer order is
    re-arranged to BN -> binarize -> conv -> pool."""

    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    #: None = follow binary_compute / packed_weights (see BinaryAlexNet).
    dense_binary_compute: Optional[str] = None
    dense_packed_weights: Optional[bool] = None
    #: Deployment-only. Unlike the VGG-style families, EVERY XNOR-Net
    #: binary layer (conv AND dense) is directly BN-followed — the
    #: maxpools come after the BN — so folding applies to both stages,
    #: each gated on that stage being packed.
    fold_bn: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        dense_packed = (
            self.packed_weights
            if self.dense_packed_weights is None
            else self.dense_packed_weights
        )
        conv_fold = self.fold_bn and bool(self.packed_weights)
        dense_fold = self.fold_bn and bool(dense_packed)
        _check_fold_training(
            self.fold_bn,
            bool(self.packed_weights) or bool(dense_packed),
            training,
        )

        def qconv(x, feat, k, **kw):
            return QuantConv(
                feat, (k, k), input_quantizer="ste_sign",
                kernel_quantizer="magnitude_aware_sign", dtype=d,
                binary_compute=self.binary_compute,
                packed_weights=self.packed_weights,
                use_bias=conv_fold,  # Carries the folded BN shift.
                pallas_interpret=self.pallas_interpret, **kw,
            )(x)

        # Stem: fp conv (never binarized), then the XNOR-Net BN->sign->conv
        # ordering for every binary layer.
        x = nn.Conv(96, (11, 11), strides=(4, 4), padding="VALID",
                    use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, d)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = qconv(x, 256, 5)
        x = _post_conv_bn(x, training, d, conv_fold)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = qconv(x, 384, 3)
        x = _post_conv_bn(x, training, d, conv_fold)
        x = qconv(x, 384, 3)
        x = _post_conv_bn(x, training, d, conv_fold)
        x = qconv(x, 256, 3)
        x = _post_conv_bn(x, training, d, conv_fold)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = x.reshape((x.shape[0], -1))
        dense_bc = (
            self.binary_compute
            if self.dense_binary_compute is None
            else self.dense_binary_compute
        )
        for u in (4096, 4096):
            x = QuantDense(
                u, input_quantizer="ste_sign",
                kernel_quantizer="magnitude_aware_sign",
                use_bias=dense_fold, dtype=d,
                binary_compute=dense_bc,
                packed_weights=dense_packed,
                pallas_interpret=self.pallas_interpret,
            )(x)
            x = _post_conv_bn(x, training, d, dense_fold)
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class XNORNet(Model):
    """XNOR-Net AlexNet (~44-45% top-1 target)."""

    binary_compute: str = Field("mxu")
    packed_weights: bool = Field(False)
    #: Dense-stage overrides; unset = follow the conv-stage settings
    #: (see BinaryAlexNet).
    dense_binary_compute: str = Field(allow_missing=True)
    dense_packed_weights: bool = Field(allow_missing=True)
    #: Deployment-only; BOTH stages fold (every XNOR-Net binary layer is
    #: directly BN-followed — see _XnorNetModule).
    fold_bn: bool = Field(False)
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _XnorNetModule(
            num_classes=num_classes, dtype=self.dtype(),
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            dense_binary_compute=getattr(self, "dense_binary_compute", None),
            dense_packed_weights=getattr(self, "dense_packed_weights", None),
            fold_bn=self.fold_bn,
            pallas_interpret=self.pallas_interpret,
        )


class _DoReFaNetModule(nn.Module):
    """DoReFa-Net (Zhou et al. 2016), w1/a2 configuration: 1-bit scaled
    weights, 2-bit uniform activations (the ``dorefa`` quantizer: clip to
    [0,1], quantize to 2^k-1 levels, STE gradient).

    Weight scaling uses magnitude_aware_sign (per-output-filter mean|W|);
    the paper scales by the LAYER mean — documented deviation (per-filter
    is strictly more expressive and costs nothing on the MXU path).
    Multi-bit activations preclude the packed binary compute paths, so the
    convs run mxu/int8 only.
    """

    num_classes: int
    dtype: Any
    activation_bits: int = 2

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        act_q = partial(dorefa, k_bit=self.activation_bits)

        def qconv(x, feat, k, **kw):
            return QuantConv(
                feat, (k, k), input_quantizer=act_q,
                kernel_quantizer="magnitude_aware_sign", dtype=d, **kw,
            )(x)

        x = nn.Conv(96, (12, 12), strides=(4, 4), padding="VALID",
                    use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, d)(x)
        x = qconv(x, 256, 5)
        x = _bn(training, d)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = qconv(x, 384, 3)
        x = _bn(training, d)(x)
        x = qconv(x, 384, 3)
        x = _bn(training, d)(x)
        x = qconv(x, 256, 3)
        x = _bn(training, d)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        x = x.reshape((x.shape[0], -1))
        for u in (4096, 4096):
            x = QuantDense(
                u, input_quantizer=act_q,
                kernel_quantizer="magnitude_aware_sign",
                use_bias=False, dtype=d,
            )(x)
            x = _bn(training, d)(x)
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class DoReFaNet(Model):
    """DoReFa-Net w1/a2 (~53% top-1 target)."""

    activation_bits: int = Field(2)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _DoReFaNetModule(
            num_classes=num_classes, dtype=self.dtype(),
            activation_bits=self.activation_bits,
        )


class _R2BBlock(nn.Module):
    """Real-to-Binary block (Martinez et al. 2020): each binary 3x3 conv
    output is rescaled by a DATA-DRIVEN per-channel gate computed from the
    conv's real-valued input (squeeze-and-excite shaped: global avgpool ->
    fp bottleneck MLP -> sigmoid), then joined by a Bi-Real-style
    real-valued shortcut.
    """

    features: int
    strides: int
    dtype: Any
    gate_reduction: int = 8
    binary_compute: str = "mxu"
    packed_weights: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        shortcut = x
        if self.strides > 1 or x.shape[-1] != self.features:
            if self.strides > 1:
                shortcut = nn.avg_pool(
                    shortcut, (2, 2), strides=(self.strides, self.strides),
                    padding="SAME",
                )
            shortcut = nn.Conv(
                self.features, (1, 1), use_bias=False, dtype=d
            )(shortcut)
            shortcut = _bn(training, d)(shortcut)
        # Gate from the REAL input (cheap fp path, O(C^2/r) params).
        g = jnp.mean(x, axis=(1, 2))
        g = nn.Dense(
            max(1, x.shape[-1] // self.gate_reduction), dtype=d
        )(g)
        g = nn.relu(g)
        g = nn.Dense(self.features, dtype=d)(g)
        g = jax.nn.sigmoid(g)
        y = QuantConv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            input_quantizer="ste_sign", kernel_quantizer="ste_sign",
            dtype=d, binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            pallas_interpret=self.pallas_interpret,
        )(x)
        y = y * g[:, None, None, :]
        y = _bn(training, d)(y)
        return y + shortcut


class _RealToBinaryNetModule(nn.Module):
    """Real-to-Binary-Net: ResNet-18 topology of R2B blocks (one shortcut
    per binary conv, as in Bi-Real)."""

    blocks_per_section: Tuple[int, ...]
    section_features: Tuple[int, ...]
    num_classes: int
    dtype: Any
    gate_reduction: int = 8
    binary_compute: str = "mxu"
    packed_weights: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        x = nn.Conv(self.section_features[0], (7, 7), strides=(2, 2),
                    padding="SAME", use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, d)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for s, (n, feat) in enumerate(
            zip(self.blocks_per_section, self.section_features)
        ):
            for b in range(n):
                strides = 2 if (b == 0 and s > 0) else 1
                x = _R2BBlock(
                    feat, strides, d, self.gate_reduction,
                    self.binary_compute, self.packed_weights,
                    self.pallas_interpret,
                )(x, training)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class RealToBinaryNet(Model):
    """Real-to-Binary-Net (~65% top-1 target with the paper's multi-stage
    KD recipe; the architecture alone trains standalone here)."""

    blocks_per_section: Sequence[int] = Field((4, 4, 4, 4))
    section_features: Sequence[int] = Field((64, 128, 256, 512))
    gate_reduction: int = Field(8)
    binary_compute: str = Field("mxu")
    packed_weights: bool = Field(False)
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _RealToBinaryNetModule(
            blocks_per_section=tuple(self.blocks_per_section),
            section_features=tuple(self.section_features),
            num_classes=num_classes,
            dtype=self.dtype(),
            gate_reduction=self.gate_reduction,
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            pallas_interpret=self.pallas_interpret,
        )


class RSign(nn.Module):
    """ReActNet's learnable-threshold sign (Liu et al. 2020): per-channel
    ``sign(x - alpha_c)``. Built on ``ste_sign``'s custom_vjp, so the STE
    gradient flows to both x and the learned shift automatically."""

    @nn.compact
    def __call__(self, x):
        alpha = self.param(
            "alpha", nn.initializers.zeros_init(), (x.shape[-1],), jnp.float32
        )
        return ste_sign(x - alpha.astype(x.dtype))


class RPReLU(nn.Module):
    """ReActNet's shifted PReLU: ``PReLU(x - gamma_c) + zeta_c`` with
    per-channel learnable shifts and slope — lets each channel reshape
    and re-center its activation distribution, which is what makes
    1-bit activations viable at MobileNet capacities."""

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        gamma = self.param("gamma", nn.initializers.zeros_init(), (c,), jnp.float32)
        zeta = self.param("zeta", nn.initializers.zeros_init(), (c,), jnp.float32)
        beta = self.param(
            "beta", nn.initializers.constant(0.25), (c,), jnp.float32
        )
        d = x.dtype
        y = x - gamma.astype(d)
        y = jnp.where(y > 0, y, beta.astype(d) * y)
        return y + zeta.astype(d)


class _ReActBlock(nn.Module):
    """One ReActNet-A unit: RSign -> binary 3x3 conv (stride s) -> BN ->
    + shortcut -> RPReLU, then RSign -> binary 1x1 conv -> BN ->
    + shortcut -> RPReLU. Channel doubling duplicates the 1x1 stage into
    two parallel branches whose outputs concatenate (each with its own
    shortcut), keeping the skip path real-valued throughout."""

    features: int
    strides: int
    dtype: Any
    binary_compute: str = "mxu"
    pallas_interpret: bool = False

    def _qconv(self, feat, k, strides=1):
        # RSign (learnable shift) binarizes OUTSIDE the conv; the inner
        # ste_sign is a forward identity on its +-1 output (and its STE
        # backward is pass-through at +-1), kept so the binary compute
        # paths validate and run.
        return QuantConv(
            feat, (k, k), strides=(strides, strides),
            input_quantizer="ste_sign",
            kernel_quantizer="ste_sign", dtype=self.dtype,
            binary_compute=self.binary_compute,
            pallas_interpret=self.pallas_interpret,
        )

    @nn.compact
    def __call__(self, x, training: bool = False):
        cin = x.shape[-1]
        # 3x3 stage.
        shortcut = x
        if self.strides > 1:
            shortcut = nn.avg_pool(
                x, (2, 2), strides=(self.strides, self.strides), padding="SAME"
            )
        y = RSign()(x)
        y = self._qconv(cin, 3, self.strides)(y)
        y = _bn(training, self.dtype)(y)
        x = RPReLU()(y + shortcut)
        # 1x1 stage (doubling -> two branches + concat).
        if self.features == cin:
            y = RSign()(x)
            y = self._qconv(cin, 1)(y)
            y = _bn(training, self.dtype)(y)
            x = RPReLU()(y + x)
        elif self.features == 2 * cin:
            outs = []
            for _ in range(2):
                y = RSign()(x)
                y = self._qconv(cin, 1)(y)
                y = _bn(training, self.dtype)(y)
                outs.append(y + x)
            x = RPReLU()(jnp.concatenate(outs, axis=-1))
        else:
            raise ValueError(
                f"ReActNet block widens {cin} -> {self.features}; only "
                "same-width or exact doubling is defined."
            )
        return x


class _ReActNetModule(nn.Module):
    """ReActNet-A: MobileNetV1 topology, every conv binarized, RSign/
    RPReLU activation reshaping. Reconstruction from the paper; the
    published 69.4% top-1 uses its two-stage KD recipe
    (DistillationExperiment covers that training pattern)."""

    features: Tuple[int, ...]
    strides: Tuple[int, ...]
    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        x = nn.Conv(self.features[0], (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, d)(x)
        for feat, s in zip(self.features[1:], self.strides):
            x = _ReActBlock(
                feat, s, d, self.binary_compute, self.pallas_interpret
            )(x, training)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class ReActNet(Model):
    """ReActNet-A (~69.4% top-1 target with the paper's KD recipe —
    beyond the larq-zoo families; demonstrates the stack extends to
    current-generation BNNs)."""

    features: Sequence[int] = Field(
        (32, 64, 128, 128, 256, 256, 512, 512, 512, 512, 512, 512, 1024,
         1024)
    )
    #: Stride of each block's 3x3 stage (len == len(features) - 1).
    strides: Sequence[int] = Field(
        (1, 2, 1, 2, 1, 2, 1, 1, 1, 1, 1, 2, 1)
    )
    binary_compute: str = Field("mxu")
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        if len(self.strides) != len(self.features) - 1:
            raise ValueError(
                f"strides has {len(self.strides)} entries; expected "
                f"{len(self.features) - 1} (one per block)."
            )
        return _ReActNetModule(
            features=tuple(self.features),
            strides=tuple(self.strides),
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=self.binary_compute,
            pallas_interpret=self.pallas_interpret,
        )


class _MeliusDenseBlock(nn.Module):
    """MeliusNet (Bethge et al. 2020) Dense Block: BN -> sign -> binary
    3x3 conv producing ``growth`` new channels, CONCATENATED onto the
    feature stack (capacity increase)."""

    growth: int
    dtype: Any
    binary_compute: str = "mxu"
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        y = _bn(training, self.dtype)(x)
        y = QuantConv(
            self.growth, (3, 3), input_quantizer="ste_sign",
            kernel_quantizer="ste_sign", dtype=self.dtype,
            binary_compute=self.binary_compute,
            pallas_interpret=self.pallas_interpret,
        )(y)
        return jnp.concatenate([x, y], axis=-1)


class _MeliusImprovementBlock(nn.Module):
    """MeliusNet Improvement Block: BN -> sign -> binary 3x3 conv whose
    output ADDS onto the newest ``growth`` channels (quality increase for
    the features the dense block just appended)."""

    growth: int
    dtype: Any
    binary_compute: str = "mxu"
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        y = _bn(training, self.dtype)(x)
        y = QuantConv(
            self.growth, (3, 3), input_quantizer="ste_sign",
            kernel_quantizer="ste_sign", dtype=self.dtype,
            binary_compute=self.binary_compute,
            pallas_interpret=self.pallas_interpret,
        )(y)
        old, new = x[..., : -self.growth], x[..., -self.growth :]
        return jnp.concatenate([old, new + y], axis=-1)


class _MeliusNetModule(nn.Module):
    """MeliusNet: sections of (Dense, Improvement) block pairs with fp
    1x1 reduction + maxpool transitions. Reconstruction from the paper's
    description (block counts/transition widths approximate, documented
    deviation like the other zoo families)."""

    blocks_per_section: Tuple[int, ...]
    transition_features: Tuple[int, ...]
    growth: int
    stem_features: int
    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        x = nn.Conv(self.stem_features, (3, 3), strides=(2, 2),
                    padding="SAME", use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, d)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for s, n_pairs in enumerate(self.blocks_per_section):
            for _ in range(n_pairs):
                x = _MeliusDenseBlock(
                    self.growth, d, self.binary_compute,
                    self.pallas_interpret,
                )(x, training)
                x = _MeliusImprovementBlock(
                    self.growth, d, self.binary_compute,
                    self.pallas_interpret,
                )(x, training)
            if s < len(self.blocks_per_section) - 1:
                x = _bn(training, d)(x)
                x = nn.relu(x)
                x = nn.Conv(
                    self.transition_features[s], (1, 1), use_bias=False,
                    dtype=d,
                )(x)
                x = nn.max_pool(x, (2, 2), strides=(2, 2), padding="SAME")
        x = _bn(training, d)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class MeliusNet22(Model):
    """MeliusNet-22 (~63.6% top-1 target): the dense-then-improve BNN
    family — capacity via concat growth, quality via residual refinement
    of the newest channels."""

    blocks_per_section: Sequence[int] = Field((4, 5, 4, 4))
    transition_features: Sequence[int] = Field((160, 224, 256))
    growth: int = Field(64)
    stem_features: int = Field(64)
    binary_compute: str = Field("mxu")
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        if len(self.transition_features) != len(self.blocks_per_section) - 1:
            raise ValueError(
                f"transition_features has {len(self.transition_features)} "
                f"entries; expected {len(self.blocks_per_section) - 1} "
                "(one per section boundary)."
            )
        return _MeliusNetModule(
            blocks_per_section=tuple(self.blocks_per_section),
            transition_features=tuple(self.transition_features),
            growth=self.growth,
            stem_features=self.stem_features,
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=self.binary_compute,
            pallas_interpret=self.pallas_interpret,
        )
