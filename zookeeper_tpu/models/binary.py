"""Binarized model zoo (larq-zoo-equivalent families).

TPU-native reconstructions of the workload ecosystem's binary
architectures (SURVEY.md §2.4/§6: BinaryNet, BinaryAlexNet, Bi-Real-Net,
QuickNet). Built from first principles against the published papers —
NOT ports of larq_zoo code; block counts/widths follow the papers and the
BASELINE.md accuracy table, with deviations noted per class.

Common recipe: latent fp32 weights, ``ste_sign``-family quantizers with
weight clipping, BatchNorm after every binary conv (binary nets are
BN-hungry), first/last layers full-precision (standard practice — they
carry too much information to binarize).
"""

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.models.base import Model
from zookeeper_tpu.ops.layers import QuantConv, QuantDense


def _bn(training: bool, dtype=jnp.float32):
    return nn.BatchNorm(
        use_running_average=not training, momentum=0.9, epsilon=1e-5,
        dtype=dtype,
    )


class _BinaryNetModule(nn.Module):
    """VGG-style BinaryNet (Courbariaux et al. 2016): the reference
    example's CIFAR/MNIST capability (SURVEY.md §2.3)."""

    features: Tuple[int, ...]
    dense_units: Tuple[int, ...]
    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        x = x.astype(self.dtype)
        for i, f in enumerate(self.features):
            # First conv: fp input (standard for binary nets) — it cannot
            # run a binary compute path, so it stays on mxu explicitly.
            quant_in = None if i == 0 else "ste_sign"
            x = QuantConv(
                f, (3, 3), input_quantizer=quant_in,
                kernel_quantizer="ste_sign", dtype=self.dtype,
                binary_compute="mxu" if i == 0 else self.binary_compute,
                packed_weights=False if i == 0 else self.packed_weights,
                pallas_interpret=self.pallas_interpret,
            )(x)
            if i % 2 == 1:
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            x = _bn(training, self.dtype)(x)
        x = x.reshape((x.shape[0], -1))
        for u in self.dense_units:
            x = QuantDense(
                u, input_quantizer="ste_sign", kernel_quantizer="ste_sign",
                use_bias=False, dtype=self.dtype,
            )(x)
            x = _bn(training, self.dtype)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


@component
class BinaryNet(Model):
    """BinaryNet VGG for CIFAR-scale inputs."""

    features: Sequence[int] = Field((128, 128, 256, 256, 512, 512))
    dense_units: Sequence[int] = Field((1024, 1024))
    #: Binary conv path: "mxu", "int8", "xnor", or "xnor_popcount"
    #: (see QuantConv).
    binary_compute: str = Field("mxu")
    #: Inference-only: params are the bit-packed kernels (32x smaller);
    #: fill from a float checkpoint with ops.packed.pack_quantconv_params.
    packed_weights: bool = Field(False)
    #: Run Pallas kernels interpreted (CPU tests).
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _BinaryNetModule(
            features=tuple(self.features),
            dense_units=tuple(self.dense_units),
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            pallas_interpret=self.pallas_interpret,
        )


class _BinaryAlexNetModule(nn.Module):
    """Binary AlexNet (larq-zoo capability row; ~36.3% top-1 target)."""

    num_classes: int
    dtype: Any
    inflation: int = 1
    binary_compute: str = "mxu"
    packed_weights: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        f = self.inflation
        # Conv1: full precision (standard for binary nets).
        x = nn.Conv(64 * f, (11, 11), strides=(4, 4), padding="SAME",
                    use_bias=False, dtype=d)(x.astype(d))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = _bn(training, self.dtype)(x)
        for feat, k in ((192 * f, 5), (384 * f, 3), (384 * f, 3), (256 * f, 3)):
            x = QuantConv(
                feat, (k, k), input_quantizer="ste_sign",
                kernel_quantizer="ste_sign", dtype=d,
                binary_compute=self.binary_compute,
                packed_weights=self.packed_weights,
                pallas_interpret=self.pallas_interpret,
            )(x)
            if feat in (192 * f, 256 * f):
                x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
            x = _bn(training, self.dtype)(x)
        x = x.reshape((x.shape[0], -1))
        for u in (4096, 4096):
            x = QuantDense(
                u, input_quantizer="ste_sign", kernel_quantizer="ste_sign",
                use_bias=False, dtype=d,
            )(x)
            x = _bn(training, self.dtype)(x)
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class BinaryAlexNet(Model):
    """Binarized AlexNet for ImageNet (BASELINE config #2)."""

    inflation: int = Field(1)
    binary_compute: str = Field("mxu")
    packed_weights: bool = Field(False)
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _BinaryAlexNetModule(
            num_classes=num_classes, dtype=self.dtype(),
            inflation=self.inflation,
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            pallas_interpret=self.pallas_interpret,
        )


class _BiRealBlock(nn.Module):
    """One Bi-Real-Net block: sign -> binary 3x3 conv -> BN -> + identity.

    The real-valued shortcut after EVERY binary conv is the signature of
    Bi-Real-Net (Liu et al. 2018); activations use approx_sign, weights
    magnitude_aware_sign.
    """

    features: int
    strides: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        shortcut = x
        if self.strides > 1 or x.shape[-1] != self.features:
            # Real-valued downsample shortcut: avgpool + fp 1x1 conv + BN.
            shortcut = nn.avg_pool(
                x, (2, 2), strides=(self.strides, self.strides), padding="SAME"
            )
            shortcut = nn.Conv(
                self.features, (1, 1), use_bias=False, dtype=self.dtype
            )(shortcut)
            shortcut = _bn(training, self.dtype)(shortcut)
        y = QuantConv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            input_quantizer="approx_sign",
            kernel_quantizer="magnitude_aware_sign", dtype=self.dtype,
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            pallas_interpret=self.pallas_interpret,
        )(x)
        y = _bn(training, self.dtype)(y)
        return y + shortcut


class _BiRealNetModule(nn.Module):
    """Bi-Real-Net-18: 7x7 fp stem, 4 sections of binary blocks."""

    blocks_per_section: Tuple[int, ...]
    section_features: Tuple[int, ...]
    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, self.dtype)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for s, (n, feat) in enumerate(
            zip(self.blocks_per_section, self.section_features)
        ):
            for b in range(n):
                strides = 2 if (b == 0 and s > 0) else 1
                x = _BiRealBlock(
                    feat, strides, d, self.binary_compute,
                    self.packed_weights, self.pallas_interpret,
                )(x, training)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class BiRealNet(Model):
    """Bi-Real-Net-18 (BASELINE config #3; ~56-57.5% top-1 target)."""

    blocks_per_section: Sequence[int] = Field((4, 4, 4, 4))
    section_features: Sequence[int] = Field((64, 128, 256, 512))
    binary_compute: str = Field("mxu")
    packed_weights: bool = Field(False)
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _BiRealNetModule(
            blocks_per_section=tuple(self.blocks_per_section),
            section_features=tuple(self.section_features),
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            pallas_interpret=self.pallas_interpret,
        )


def _blur_pool(x: jax.Array, dtype) -> jax.Array:
    """Anti-aliased stride-2 downsampling (Zhang 2019), used by QuickNet
    transitions: fixed 3x3 binomial filter, depthwise, stride 2."""
    c = x.shape[-1]
    f = jnp.array([1.0, 2.0, 1.0], dtype)
    k2d = jnp.outer(f, f)
    k2d = k2d / k2d.sum()
    kernel = jnp.tile(k2d[:, :, None, None], (1, 1, 1, c))  # HWIO, I=1 (dw)
    return jax.lax.conv_general_dilated(
        x, kernel, window_strides=(2, 2), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )


class _QuickNetModule(nn.Module):
    """QuickNet family (Bannink et al. 2021, "Larq Compute Engine" /
    larq-zoo sota): fp stem, sections of residual binary 3x3 convs, fp
    pointwise transition with blurpool downsampling.

    Reconstruction from the paper's description; exact stem/transition
    minutiae may deviate from larq_zoo (documented deviation, SURVEY.md §6
    accuracies are approximate targets).
    """

    blocks_per_section: Tuple[int, ...]
    section_features: Tuple[int, ...]
    num_classes: int
    dtype: Any
    binary_compute: str = "mxu"
    packed_weights: bool = False
    pallas_interpret: bool = False

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        # Stem: fp 3x3/2 to 8ch, then grouped 3x3/2 to first section width.
        x = nn.Conv(8, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d)(x.astype(d))
        x = _bn(training, self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(
            self.section_features[0], (3, 3), strides=(2, 2), padding="SAME",
            use_bias=False, feature_group_count=4, dtype=d,
        )(x)
        x = _bn(training, self.dtype)(x)
        for s, (n, feat) in enumerate(
            zip(self.blocks_per_section, self.section_features)
        ):
            if s > 0:
                # Transition: blurpool downsample + fp 1x1 conv to widen.
                x = nn.relu(x)
                x = _blur_pool(x, d)
                x = nn.Conv(feat, (1, 1), use_bias=False, dtype=d)(x)
                x = _bn(training, self.dtype)(x)
            for _ in range(n):
                y = QuantConv(
                    feat, (3, 3), input_quantizer="ste_sign",
                    kernel_quantizer="ste_sign", dtype=d,
                    binary_compute=self.binary_compute,
                    packed_weights=self.packed_weights,
                    pallas_interpret=self.pallas_interpret,
                )(x)
                y = _bn(training, d)(y)
                x = x + y  # Residual around every binary conv.
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class QuickNet(Model):
    """QuickNet (~63.3% top-1 target; BASELINE configs #4)."""

    blocks_per_section: Sequence[int] = Field((2, 3, 4, 4))
    section_features: Sequence[int] = Field((64, 128, 256, 512))
    binary_compute: str = Field("mxu")
    packed_weights: bool = Field(False)
    pallas_interpret: bool = Field(False)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _QuickNetModule(
            blocks_per_section=tuple(self.blocks_per_section),
            section_features=tuple(self.section_features),
            num_classes=num_classes,
            dtype=self.dtype(),
            binary_compute=self.binary_compute,
            packed_weights=self.packed_weights,
            pallas_interpret=self.pallas_interpret,
        )


@component
class QuickNetSmall(QuickNet):
    section_features: Sequence[int] = Field((32, 64, 256, 512))


@component
class QuickNetLarge(QuickNet):
    """QuickNet-Large (~66.9% top-1 target; the north-star workload)."""

    blocks_per_section: Sequence[int] = Field((6, 8, 12, 6))
