"""Abstract Model component."""

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from zookeeper_tpu.core import Field, component


@component
class Model:
    """A component that builds a ``flax.linen.Module``.

    Reference contract (SURVEY.md §2.2 `zookeeper/tf/model.py`
    [unverified]): pure interface; all architecture lives in subclasses.
    Modules built here follow one call convention:

        module.apply(variables, x, training=bool, mutable=[...])

    with ``x`` batched NHWC (or [batch, features]) and ``training``
    switching BatchNorm/dropout behavior.
    """

    #: Compute dtype for activations. Params stay float32; bfloat16 here is
    #: the standard TPU mixed-precision recipe (MXU-native, no loss scaling
    #: needed thanks to the float32 accumulate + wide exponent).
    compute_dtype: str = Field("float32")

    def build(self, input_shape: Sequence[int], num_classes: int) -> nn.Module:
        raise NotImplementedError("Model subclasses must implement build().")

    def dtype(self):
        return jnp.dtype(self.compute_dtype)

    def initialize(
        self,
        module: nn.Module,
        input_shape: Sequence[int],
        seed: int = 0,
    ) -> Tuple[Any, Any]:
        """Init variables with a dummy batch; returns (params, model_state)
        where model_state holds the non-trainable collections (e.g.
        BatchNorm's ``batch_stats``)."""
        rng = jax.random.PRNGKey(seed)
        dummy = jnp.zeros((1, *input_shape), self.dtype())
        variables = module.init(rng, dummy, training=False)
        params = variables.pop("params")
        return params, variables
