"""Causal transformer language model — the long-context model family.

Beyond the reference's CNN contract (SURVEY.md §2.3 scopes the zoo to
image classifiers), but the brief makes long-context first-class and the
attention tiers (``ops.flash_attention`` / ``ring_attention`` /
``ring_flash_attention``) need a MODEL surface, not just bare ops: this
is the family that exercises them through the same ``Model`` /
``configure`` / ``TrainingExperiment`` machinery as the CNN zoo.

Design (TPU-first, standard pre-norm decoder):

- pre-RMSNorm blocks, GELU MLP, learned positional embedding, weight-
  tied LM head (embed.T) — the shapes XLA tiles well on the MXU
  (d_model/heads chosen so head_dim lands on 64/128 lanes);
- attention runs the Pallas flash kernel by default (``attention=
  "flash"``): O(block) VMEM at any sequence length, measured 2.5-5x
  faster fwd+bwd than the dense path and trains s=16k where dense OOMs
  (BASELINE.md round-7); ``"dense"`` keeps the reference oracle path;
- the module is pure (no mesh assumptions): data parallelism comes from
  the Partitioner sharding the batch; SEQUENCE parallelism composes at
  the ops layer (``ring_flash_attention`` inside a shard_map over a
  mesh with the sequence axis — see ``ops/attention.py``);
- the existing jittable train step works unchanged: ``softmax_cross_
  entropy`` and ``accuracy`` broadcast over the position dimension
  (logits ``[b, s, vocab]``, targets ``[b, s]``), so an LM batch is
  ``{"input": tokens, "target": next_tokens}`` and ``make_train_step``
  / ``TrainingExperiment`` need no LM-specific fork.
"""

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.models.base import Model
from zookeeper_tpu.ops import (
    attention_reference,
    cached_attention,
    flash_attention,
    paged_decode_attention,
)
from zookeeper_tpu.parallel.sharding import constrain_batch_sharded


def _resolve_attention(attention):
    """``"flash"`` / ``"dense"`` / any ``callable(q, k, v, *, causal)``
    — the callable form is how sequence parallelism plugs in (e.g.
    ``partial(ring_flash_attention, mesh=mesh, seq_axis="sp",
    batch_axis="data")`` shards the attention over a mesh while the
    rest of the model runs an ordinary pjit program). Checked at the
    MODULE level too (it is public API): a typo'd tier must not
    silently fall back to dense — at s=16k that materializes the
    [s, s] scores and OOMs."""
    if callable(attention):
        return attention
    if attention == "flash":
        return flash_attention
    if attention == "dense":
        return attention_reference
    raise ValueError(
        f"attention={attention!r}: expected 'flash', 'dense', or an "
        "attention callable."
    )


def _resolve_paged_attention(paged_attention):
    """``"reference"`` / ``"pallas"`` / any ``callable(q, k_pool,
    v_pool, page_table, lengths, *, k_scale=None, v_scale=None)`` — the
    page-pool analogue of :func:`_resolve_decode_attention`
    (docs/DESIGN.md §20). ``"reference"`` is the
    :func:`~zookeeper_tpu.ops.pool_decode_attention` gather+einsum
    oracle; ``"pallas"`` the page-table scalar-prefetch kernel; the
    callable form is how the decode engine injects the mesh-composed
    sharded wrapper."""
    from zookeeper_tpu.ops import (
        pool_decode_attention,
        pool_paged_decode_attention,
    )

    if callable(paged_attention):
        return paged_attention
    if paged_attention == "reference":
        return pool_decode_attention
    if paged_attention == "pallas":
        return pool_paged_decode_attention
    raise ValueError(
        f"paged attention={paged_attention!r}: expected 'reference', "
        "'pallas', or a callable(q, k_pool, v_pool, page_table, "
        "lengths)."
    )


def _pool_write_rows(layer, rows, pages, offsets):
    """Scatter ``rows [b(, w), heads, head_dim]`` into a page-pool
    layer dict at ``(pages, offsets)`` (same leading shape; entries
    with ``page == num_pages`` drop — the OOB sentinel covering
    inactive slots, unallocated table entries, and padding rows).
    Quantizes inline when the layer carries scale arrays (int8 pools —
    see ``ops.quantizers.quantize_kv_rows``). Returns the updated
    layer dict."""
    out = dict(layer)
    for name, scale_name in (("k", "k_scale"), ("v", "v_scale")):
        buf = layer[name]
        vals = rows[name]
        if scale_name in layer:
            from zookeeper_tpu.ops import quantize_kv_rows

            q, s = quantize_kv_rows(vals)
            out[name] = buf.at[pages, offsets].set(q, mode="drop")
            out[scale_name] = layer[scale_name].at[pages, offsets].set(
                s, mode="drop"
            )
        else:
            out[name] = buf.at[pages, offsets].set(
                vals.astype(buf.dtype), mode="drop"
            )
    return out


def _pool_scales(layer):
    return layer.get("k_scale"), layer.get("v_scale")


def _resolve_decode_attention(decode_attention):
    """``"reference"`` / ``"pallas"`` / any ``callable(q, k_cache,
    v_cache, lengths)`` — the decode-path analogue of
    :func:`_resolve_attention`. ``"reference"`` is the
    :func:`cached_attention` oracle einsum; ``"pallas"`` the
    length-aware paged decode kernel (auto interpret off-TPU); the
    callable form is how the decode engine injects the mesh-composed
    ``sharded_paged_decode_attention`` (or any future flavor) without
    rebuilding the module — see ``DecodeEngine.decode_attention``."""
    if callable(decode_attention):
        return decode_attention
    if decode_attention == "reference":
        return cached_attention
    if decode_attention == "pallas":
        return paged_decode_attention
    raise ValueError(
        f"decode_attention={decode_attention!r}: expected 'reference', "
        "'pallas', or a callable(q, k_cache, v_cache, lengths)."
    )


class RMSNorm(nn.Module):
    """Root-mean-square layernorm (no mean subtraction, no bias): the
    cheaper norm that long-context transformer stacks standardized on;
    fp32 statistics regardless of compute dtype."""

    dtype: Any = jnp.float32
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        x32 = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        y = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        return (y * scale).astype(self.dtype)


class _Block(nn.Module):
    """One pre-norm decoder block.

    ``setup()``-structured (not ``nn.compact``) so the SAME weights
    serve two traced programs: the full-context ``__call__`` (training
    / prefill) and the single-position :meth:`decode` (cached
    attention over a KV buffer). Submodule names are pinned to the
    names the original compact implementation auto-assigned
    (``RMSNorm_0``/``RMSNorm_1``/``qkv``/``proj``/``up``/``down``) so
    every existing checkpoint and partition rule keeps matching.
    """

    d_model: int
    num_heads: int
    mlp_ratio: int
    attention: Any
    dtype: Any
    pin_activations: bool = True
    #: Decode-path attention flavor: "reference" (the cached_attention
    #: oracle), "pallas" (the paged decode kernel), or a callable. A
    #: per-call ``attention_override`` (the engine seam) wins.
    decode_attention: Any = "reference"

    def setup(self):
        d = self.d_model
        self.ln1 = RMSNorm(dtype=self.dtype, name="RMSNorm_0")
        self.wqkv = nn.Dense(
            3 * d, use_bias=False, dtype=self.dtype, name="qkv"
        )
        self.wproj = nn.Dense(d, use_bias=False, dtype=self.dtype, name="proj")
        self.ln2 = RMSNorm(dtype=self.dtype, name="RMSNorm_1")
        self.wup = nn.Dense(
            self.mlp_ratio * d, use_bias=False, dtype=self.dtype, name="up"
        )
        self.wdown = nn.Dense(d, use_bias=False, dtype=self.dtype, name="down")

    def _mlp(self, x):
        h = self.ln2(x)
        h = self.wup(h)
        h = nn.gelu(h)
        h = self.wdown(h)
        # Pin the residual stream to the canonical layout (batch on the
        # data axes) at every block boundary: without the pin, GSPMD
        # was observed picking an FSDP-axis-spread layout for the
        # attention intermediates it then could not reshard — the same
        # involuntary-full-remat pathology the CNN Quant layers pin
        # against (parallel/sharding.py). No-op outside a mesh scope;
        # see ``_auto_pin_activations`` for when the pin is skipped.
        out = x + h
        if self.pin_activations:
            out = constrain_batch_sharded(out)
        return out

    def __call__(self, x, training: bool, return_kv: bool = False):
        b, s, d = x.shape
        head_dim = d // self.num_heads

        h = self.ln1(x)
        qkv = self.wqkv(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(b, s, self.num_heads, head_dim)
        kh, vh = to_heads(k), to_heads(v)
        attn = _resolve_attention(self.attention)
        o = attn(to_heads(q), kh, vh, causal=True)
        x = x + self.wproj(o.reshape(b, s, d))
        out = self._mlp(x)
        if return_kv:
            return out, (kh, vh)
        return out

    def decode(self, x, k_cache, v_cache, lengths, attention_override=None):
        """One cached-attention step: ``x [b, 1, d]`` is the new token's
        residual stream, ``k_cache/v_cache [b, capacity, heads,
        head_dim]`` the slot KV buffers, ``lengths [b]`` the tokens
        already cached. Writes the new position's K/V at index
        ``lengths`` (clamped to the last row — the scheduler never
        decodes past capacity; the clamp only keeps an inactive slot's
        idle write in bounds), attends rows ``0..lengths``, and returns
        ``(x_out, k_cache, v_cache)``. Same projections/norms as
        ``__call__`` — the weights are literally the same submodules.
        The attention over the cache runs ``attention_override`` when
        given (the decode engine's flavor seam), else the block's
        ``decode_attention`` setting."""
        b = x.shape[0]
        head_dim = self.d_model // self.num_heads

        h = self.ln1(x)
        qkv = self.wqkv(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(b, 1, self.num_heads, head_dim)
        q, k, v = to_heads(q), to_heads(k), to_heads(v)
        write = jnp.clip(lengths, 0, k_cache.shape[1] - 1)
        rows = jnp.arange(b)
        k_cache = k_cache.at[rows, write].set(k[:, 0], mode="drop")
        v_cache = v_cache.at[rows, write].set(v[:, 0], mode="drop")
        attn = (
            attention_override
            if attention_override is not None
            else _resolve_decode_attention(self.decode_attention)
        )
        o = attn(q, k_cache, v_cache, lengths)
        x = x + self.wproj(o.reshape(b, 1, self.d_model))
        return self._mlp(x), k_cache, v_cache

    def decode_verify(self, x, k_cache, v_cache, lengths):
        """The multi-token (speculative verify) step: ``x [b, w, d]`` is
        the residual stream of ``w`` draft positions (position ``j`` is
        the token at sequence index ``lengths + j``), appended to the
        cache in ONE dispatch — all ``w`` new K/V rows land via a
        per-slot dynamic-update-slice at ``lengths``
        (``cache.append_kv_rows``) and every position attends
        cache+window causally (``ops.verify_cached_attention``: row
        ``j`` sees cache rows ``0..lengths+j``). Same submodules as
        ``__call__``/``decode`` — one weight set, three traced programs.
        Rollback-by-length: the caller commits only the accepted prefix
        by advancing ``lengths`` that far; rejected rows stay masked
        garbage (docs/DESIGN.md §18)."""
        from zookeeper_tpu.ops import verify_cached_attention
        from zookeeper_tpu.serving.decode.cache import append_kv_rows

        b, w, _ = x.shape
        head_dim = self.d_model // self.num_heads

        h = self.ln1(x)
        qkv = self.wqkv(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(b, w, self.num_heads, head_dim)
        q, k, v = to_heads(q), to_heads(k), to_heads(v)
        k_cache = append_kv_rows(k_cache, k, lengths)
        v_cache = append_kv_rows(v_cache, v, lengths)
        o = verify_cached_attention(q, k_cache, v_cache, lengths)
        x = x + self.wproj(o.reshape(b, w, self.d_model))
        return self._mlp(x), k_cache, v_cache

    def decode_paged(
        self, x, layer, page_table, lengths, attention_override=None
    ):
        """The page-pool twin of :meth:`decode` (docs/DESIGN.md §20):
        ``layer`` is a pool dict (``k``/``v`` ``[num_pages, page_size,
        heads, head_dim]``, plus scale arrays for int8 pools) shared by
        EVERY slot; the new position's K/V row lands at ``(page_table[
        slot, lengths // page_size], lengths % page_size)`` — the
        indirected write — and the attention reads through the table
        (``ops.pool_decode_attention`` or the injected kernel). A slot
        whose write target is unallocated (``-1`` table entry, or an
        inactive slot past its pages) drops the write via the OOB page
        sentinel — the paged analogue of the §15 clamp, and like it
        only ever taken by slots whose output is discarded."""
        b = x.shape[0]
        head_dim = self.d_model // self.num_heads
        num_pages, ps = layer["k"].shape[0], layer["k"].shape[1]

        h = self.ln1(x)
        qkv = self.wqkv(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(b, 1, self.num_heads, head_dim)
        q, k, v = to_heads(q), to_heads(k), to_heads(v)
        row = jnp.clip(lengths // ps, 0, page_table.shape[1] - 1)
        page = jnp.take_along_axis(page_table, row[:, None], axis=1)[:, 0]
        page = jnp.where(
            (page < 0) | (lengths >= page_table.shape[1] * ps),
            num_pages,
            page,
        )
        off = lengths % ps
        layer = _pool_write_rows(
            layer, {"k": k[:, 0], "v": v[:, 0]}, page, off
        )
        attn = (
            attention_override
            if attention_override is not None
            else _resolve_paged_attention(self.decode_attention)
        )
        k_scale, v_scale = _pool_scales(layer)
        o = attn(
            q, layer["k"], layer["v"], page_table, lengths,
            k_scale=k_scale, v_scale=v_scale,
        )
        x = x + self.wproj(o.reshape(b, 1, self.d_model))
        return self._mlp(x), layer

    def decode_verify_paged(
        self, x, layer, page_table, lengths, valid=None,
        attention_override=None,
    ):
        """The page-pool twin of :meth:`decode_verify`: all ``w``
        window rows scatter through the page table in one dispatch
        (position ``lengths + j`` → its table-resolved page/offset, so
        a window crossing a page boundary just lands in two pages), and
        every position attends cache+window through
        ``ops.pool_verify_attention``. ``valid [b]`` bounds how many
        window rows are REAL per slot (the warm-prefix extend program's
        padding rows write nowhere — OOB sentinel); None = all ``w``
        (the speculative verify, whose eligibility check already
        guarantees the pages exist). Rollback stays by-length."""
        b, w, _ = x.shape
        head_dim = self.d_model // self.num_heads
        num_pages, ps = layer["k"].shape[0], layer["k"].shape[1]

        h = self.ln1(x)
        qkv = self.wqkv(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(b, w, self.num_heads, head_dim)
        q, k, v = to_heads(q), to_heads(k), to_heads(v)
        pos = lengths[:, None] + jnp.arange(w)[None, :]
        row = jnp.clip(pos // ps, 0, page_table.shape[1] - 1)
        page = jnp.take_along_axis(page_table, row, axis=1)
        dead = (page < 0) | (pos >= page_table.shape[1] * ps)
        if valid is not None:
            dead = dead | (jnp.arange(w)[None, :] >= valid[:, None])
        page = jnp.where(dead, num_pages, page)
        off = pos % ps
        layer = _pool_write_rows(layer, {"k": k, "v": v}, page, off)
        k_scale, v_scale = _pool_scales(layer)
        from zookeeper_tpu.ops import pool_verify_attention

        attn = (
            attention_override
            if attention_override is not None
            else pool_verify_attention
        )
        o = attn(
            q, layer["k"], layer["v"], page_table, lengths,
            k_scale=k_scale, v_scale=v_scale,
        )
        x = x + self.wproj(o.reshape(b, w, self.d_model))
        return self._mlp(x), layer


def _auto_pin_activations(attention, pin_activations):
    """Whether the residual-stream pins apply. ``None`` (the default)
    auto-selects: pinned for the within-chip tiers (incl. the bare
    ``flash_attention``/``attention_reference`` callables — they are
    functionally identical to their string forms and need the same
    FSDP protection), skipped for any OTHER callable, which is assumed
    mesh-composed sequence parallelism: the SP op owns the
    sequence-sharded layout, and the ambient scope's canonical spec
    (which reads every non-data axis as a CHANNEL axis) would pin
    d_model over the sequence axis and fight it. Pass an explicit bool
    to override either way (e.g. ``True`` for a custom within-chip
    kernel under FSDP)."""
    if pin_activations is not None:
        return pin_activations
    return (
        not callable(attention)
        or attention in (flash_attention, attention_reference)
    )


class TransformerLMModule(nn.Module):
    """The causal LM module. ``setup()``-structured so three methods
    share one weight set and one param tree (names unchanged from the
    original compact layout):

    - ``__call__`` — the full-context forward (training, eval, the
      full-recompute ``greedy_decode`` oracle).
    - ``prefill`` — full-context forward that ALSO returns every
      layer's K/V heads (to seed a decode engine's KV cache) and the
      next-token logits at each sequence's true last position.
    - ``decode_step`` — one token per sequence through the cached-
      attention path (``ops.cached_attention``) over caller-owned KV
      buffers.

    Prefill/decode share weights AND numerics with ``__call__`` by
    construction — same submodules, same einsum/precision discipline —
    which is what the decode-parity certification pins
    (docs/DESIGN.md §15).
    """

    vocab_size: int
    num_layers: int
    d_model: int
    num_heads: int
    mlp_ratio: int
    attention: Any  # "flash" | "dense" | callable(q, k, v, *, causal)
    max_seq_len: int
    dtype: Any
    #: None = auto (see ``_auto_pin_activations``); bool overrides.
    pin_activations: Any = None
    #: Decode-path attention flavor ("reference" | "pallas" |
    #: callable); a ``decode_step`` per-call override wins — see
    #: ``_resolve_decode_attention``.
    decode_attention: Any = "reference"

    def setup(self):
        self.embed = self.param(
            "embed",
            nn.initializers.normal(0.02),
            (self.vocab_size, self.d_model),
        )
        self.pos = self.param(
            "pos",
            nn.initializers.normal(0.02),
            (self.max_seq_len, self.d_model),
        )
        pin = _auto_pin_activations(self.attention, self.pin_activations)
        self.blocks = [
            _Block(
                d_model=self.d_model,
                num_heads=self.num_heads,
                mlp_ratio=self.mlp_ratio,
                attention=self.attention,
                dtype=self.dtype,
                pin_activations=pin,
                decode_attention=self.decode_attention,
                name=f"block{i}",
            )
            for i in range(self.num_layers)
        ]
        self.final_norm = RMSNorm(dtype=self.dtype, name="RMSNorm_0")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    def _pin(self) -> bool:
        return _auto_pin_activations(self.attention, self.pin_activations)

    def _logits(self, x):
        x = self.final_norm(x)
        # Weight-tied LM head: logits in fp32 (the loss reduction dtype).
        return jnp.einsum(
            "bsd,vd->bsv",
            x.astype(jnp.float32),
            self.embed.astype(jnp.float32),
        )

    def _backbone(self, tokens, training: bool, collect_kv: bool):
        if tokens.ndim != 2:
            raise ValueError(
                f"TransformerLM expects [batch, seq] int tokens, got "
                f"shape {tokens.shape}."
            )
        s = tokens.shape[1]
        if s > self.max_seq_len:
            raise ValueError(
                f"Sequence length {s} exceeds max_seq_len "
                f"{self.max_seq_len} (the positional table size)."
            )
        x = (self.embed[tokens] + self.pos[None, :s]).astype(self.dtype)
        if self._pin():
            x = constrain_batch_sharded(x)
        kv = []
        for block in self.blocks:
            if collect_kv:
                x, layer_kv = block(x, training, return_kv=True)
                kv.append(layer_kv)
            else:
                x = block(x, training)
        return x, kv

    def __call__(self, tokens, training: bool = False):
        x, _ = self._backbone(tokens, training, collect_kv=False)
        return self._logits(x)

    def prefill(self, tokens, lengths):
        """Write-path of the decode engine's two-program split: run the
        ordinary full-context forward over a right-padded prompt batch
        ``tokens [b, s]`` (``lengths [b]`` true prompt lengths), and
        return ``(last_logits [b, vocab], kv)`` where ``last_logits``
        is each sequence's next-token distribution at its TRUE last
        position (right padding cannot influence it — causal) and
        ``kv`` is a per-layer tuple of ``(k, v) [b, s, heads,
        head_dim]`` head tensors for the caller to scatter into its KV
        cache. Numerically the same program as ``__call__`` — the
        first emitted token is the full-context oracle's."""
        x, kv = self._backbone(tokens, False, collect_kv=True)
        logits = self._logits(x)
        idx = jnp.clip(lengths - 1, 0, tokens.shape[1] - 1)
        last = jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0]
        return last, tuple(kv)

    def decode_step(self, tokens, lengths, cache, attention_override=None):
        """One incremental token per sequence. ``tokens [b] int`` are
        the CURRENT input tokens (each sits at position ``lengths``),
        ``cache`` is a per-layer tuple of ``{"k", "v"}`` buffers
        ``[b, capacity, heads, head_dim]``. Returns ``(logits [b,
        vocab], new_cache)`` — the caller owns length bookkeeping and
        feeds ``argmax(logits)`` back as the next step's ``tokens``.
        ``attention_override`` (a ``callable(q, k_cache, v_cache,
        lengths)``) selects the cache-attention flavor for THIS trace,
        overriding the module's ``decode_attention`` — the seam the
        decode engine threads its config-selected kernel (or the
        mesh-composed sharded wrapper) through without rebuilding the
        module."""
        if len(cache) != self.num_layers:
            raise ValueError(
                f"cache has {len(cache)} layers, model has "
                f"{self.num_layers}."
            )
        pos_idx = jnp.clip(lengths, 0, self.max_seq_len - 1)
        x = (self.embed[tokens] + self.pos[pos_idx]).astype(self.dtype)
        x = x[:, None, :]
        if self._pin():
            x = constrain_batch_sharded(x)
        new_cache = []
        for block, layer in zip(self.blocks, cache):
            x, kc, vc = block.decode(
                x, layer["k"], layer["v"], lengths,
                attention_override=attention_override,
            )
            new_cache.append({"k": kc, "v": vc})
        return self._logits(x)[:, 0], tuple(new_cache)

    def decode_verify(self, tokens, lengths, cache):
        """``w`` tokens per sequence through the cached-attention path
        in ONE dispatch — the speculative-decode verify/append program
        (docs/DESIGN.md §18). ``tokens [b, w] int`` are the window's
        input tokens (token ``j`` sits at position ``lengths + j``),
        ``cache`` the per-layer ``{"k", "v"}`` buffers. Returns
        ``(logits [b, w, vocab], new_cache)`` with all ``w`` K/V rows
        appended per layer (``cache.append_kv_rows``); ``logits[:, j]``
        is the next-token distribution AFTER consuming token ``j`` —
        the verify scores for greedy acceptance. The caller owns length
        bookkeeping: advancing ``lengths`` by only the accepted prefix
        is the whole rollback contract (rejected rows stay at
        ``j >= length`` where every attention path masks them).
        Positions past the table clamp like ``decode_step``'s — the
        scheduler never COMMITS past ``token_limit``, so a clamped row
        is never attended. At ``w == 1`` this computes exactly what
        ``decode_step`` computes (same ops, ``verify_cached_attention``
        reduces to ``cached_attention``)."""
        if len(cache) != self.num_layers:
            raise ValueError(
                f"cache has {len(cache)} layers, model has "
                f"{self.num_layers}."
            )
        if tokens.ndim != 2:
            raise ValueError(
                f"decode_verify expects [batch, w] int tokens, got "
                f"shape {tokens.shape}."
            )
        w = tokens.shape[1]
        pos_idx = jnp.clip(
            lengths[:, None] + jnp.arange(w)[None, :],
            0,
            self.max_seq_len - 1,
        )
        x = (self.embed[tokens] + self.pos[pos_idx]).astype(self.dtype)
        if self._pin():
            x = constrain_batch_sharded(x)
        new_cache = []
        for block, layer in zip(self.blocks, cache):
            x, kc, vc = block.decode_verify(
                x, layer["k"], layer["v"], lengths
            )
            new_cache.append({"k": kc, "v": vc})
        return self._logits(x), tuple(new_cache)

    def decode_step_paged(
        self, tokens, lengths, cache, page_table, attention_override=None
    ):
        """:meth:`decode_step` over a SHARED page pool (docs/DESIGN.md
        §20): ``cache`` is a per-layer tuple of pool dicts (``k``/``v``
        ``[num_pages, page_size, heads, head_dim]``, plus
        ``k_scale``/``v_scale`` for int8 pools), ``page_table [b,
        max_pages] int32`` resolves each sequence's logical pages.
        Same contract otherwise — the caller owns lengths, the new K/V
        row is written (through the table) before attending, and
        ``attention_override`` is the engine's paged-flavor seam
        (``callable(q, k_pool, v_pool, page_table, lengths, *,
        k_scale=None, v_scale=None)``)."""
        if len(cache) != self.num_layers:
            raise ValueError(
                f"cache has {len(cache)} layers, model has "
                f"{self.num_layers}."
            )
        pos_idx = jnp.clip(lengths, 0, self.max_seq_len - 1)
        x = (self.embed[tokens] + self.pos[pos_idx]).astype(self.dtype)
        x = x[:, None, :]
        if self._pin():
            x = constrain_batch_sharded(x)
        new_cache = []
        for block, layer in zip(self.blocks, cache):
            x, new_layer = block.decode_paged(
                x, layer, page_table, lengths,
                attention_override=attention_override,
            )
            new_cache.append(new_layer)
        return self._logits(x)[:, 0], tuple(new_cache)

    def decode_verify_paged(
        self, tokens, lengths, cache, page_table, valid=None,
        attention_override=None,
    ):
        """:meth:`decode_verify` over a shared page pool: ``w`` window
        tokens per sequence scatter through the page table in one
        dispatch (windows cross page boundaries freely) and every
        position's logits come back for acceptance scoring — ALSO the
        warm-prefix extend program (docs/DESIGN.md §20): a prompt whose
        prefix is cache-resident enters here with the SUFFIX as the
        window (``valid [b]`` = true suffix lengths; padding rows write
        nowhere), each suffix position attending the shared prefix
        pages it never recomputed — which is the entire TTFT win."""
        if len(cache) != self.num_layers:
            raise ValueError(
                f"cache has {len(cache)} layers, model has "
                f"{self.num_layers}."
            )
        if tokens.ndim != 2:
            raise ValueError(
                f"decode_verify_paged expects [batch, w] int tokens, "
                f"got shape {tokens.shape}."
            )
        w = tokens.shape[1]
        pos_idx = jnp.clip(
            lengths[:, None] + jnp.arange(w)[None, :],
            0,
            self.max_seq_len - 1,
        )
        x = (self.embed[tokens] + self.pos[pos_idx]).astype(self.dtype)
        if self._pin():
            x = constrain_batch_sharded(x)
        new_cache = []
        for block, layer in zip(self.blocks, cache):
            x, new_layer = block.decode_verify_paged(
                x, layer, page_table, lengths, valid=valid,
                attention_override=attention_override,
            )
            new_cache.append(new_layer)
        return self._logits(x), tuple(new_cache)


def greedy_decode(
    module: nn.Module, variables: Any, prompt: Any, steps: int
) -> jax.Array:
    """Greedy argmax continuation: ``[batch, t0]`` int tokens ->
    ``[batch, t0 + steps]``. Each step recomputes the FULL context
    (one jitted forward per emitted token, no KV cache) — a smoke/debug
    utility for eyeballing what a trained LM memorized and the seed of
    a future incremental-decode serving path, not a serving path
    itself. Deterministic by construction (argmax, no sampling).

    The module's positional table bounds the total length: building
    with ``max_seq_len`` headroom (an explicit capacity larger than
    the training ``seq_len``) is what makes room to decode past the
    training window.
    """
    if steps < 0:
        raise ValueError(f"steps={steps} must be >= 0.")
    tokens = jnp.asarray(prompt)
    if tokens.ndim != 2:
        raise ValueError(
            f"prompt must be [batch, t0] int tokens, got {tokens.shape}."
        )
    cap = getattr(module, "max_seq_len", None)
    if cap is not None and tokens.shape[1] + steps > cap:
        raise ValueError(
            f"prompt length {tokens.shape[1]} + steps {steps} exceeds "
            f"the positional table capacity {cap}; build the model with "
            "a larger max_seq_len to decode further."
        )
    # One executable per total length (steps distinct compiles): fine
    # for a smoke utility; an incremental decoder would bucket lengths.
    forward = jax.jit(
        lambda v, t: module.apply(v, t, training=False)
    )
    for _ in range(int(steps)):
        logits = forward(variables, tokens)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(tokens.dtype)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens


@component
class TransformerLM(Model):
    """Causal LM model component (see module docstring).

    ``build(input_shape=(seq_len,), num_classes=vocab_size)`` follows
    the Model contract — the "classes" of a language model are its
    vocabulary, scored at every position.
    """

    num_layers: int = Field(4)
    d_model: int = Field(256)
    num_heads: int = Field(4)
    mlp_ratio: int = Field(4)
    #: "flash" (Pallas kernels, long-context default) or "dense" (the
    #: oracle path).
    attention: str = Field("flash")
    #: Decode-path (KV-cache) attention flavor: "reference" (the
    #: ``cached_attention`` oracle einsum — reads the full capacity
    #: axis every step) or "pallas" (the length-aware paged decode
    #: kernel). The DEFAULT stays the reference so direct module users
    #: keep oracle numerics; the serving engine's own
    #: ``decode_attention="auto"`` Field selects the kernel on TPU —
    #: see ``DecodeEngine``.
    decode_attention: str = Field("reference")
    #: Positional-table capacity. -1 (the default) sizes it to the
    #: sequence length ``build()`` receives — the common case, and it
    #: keeps one ``seq_len`` knob sufficient in CLI tasks. Set
    #: explicitly to train short now and run longer contexts later
    #: without a table reshape; build() raises if the configured
    #: sequence exceeds an explicit capacity.
    max_seq_len: int = Field(-1)

    def set_attention_override(self, fn) -> None:
        """The partitioner injection seam (``Partitioner.prepare_model``):
        a mesh-owning partitioner (``SequenceParallelPartitioner``)
        installs its attention callable here BEFORE ``build()``, which
        then takes precedence over the string ``attention`` Field — so
        sequence-parallel recipes drive from the CLI without hand-wiring
        callables into model configs. ``None`` clears the override."""
        if fn is not None and not callable(fn):
            raise ValueError(
                f"attention override must be callable(q, k, v, *, "
                f"causal) or None, got {fn!r}."
            )
        object.__setattr__(self, "_attention_override", fn)

    def set_decode_attention_override(self, fn) -> None:
        """The decode-path twin of :meth:`set_attention_override`: a
        mesh-owning caller installs a ``callable(q, k_cache, v_cache,
        lengths)`` here before ``build()`` and it takes precedence over
        the string ``decode_attention`` Field. ``None`` clears."""
        if fn is not None and not callable(fn):
            raise ValueError(
                f"decode attention override must be callable(q, k_cache, "
                f"v_cache, lengths) or None, got {fn!r}."
            )
        object.__setattr__(self, "_decode_attention_override", fn)

    def build(self, input_shape: Sequence[int], num_classes: int) -> nn.Module:
        if len(input_shape) != 1:
            raise ValueError(
                f"TransformerLM input_shape must be (seq_len,), got "
                f"{tuple(input_shape)}."
            )
        # One source of truth for valid tiers (the Field is a string;
        # callables plug in at the MODULE level — see
        # ``_resolve_attention``). An injected override (the
        # partitioner seam above) wins over the Field.
        attention = getattr(self, "_attention_override", None)
        if attention is None:
            _resolve_attention(self.attention)
            attention = self.attention
        decode_attention = getattr(self, "_decode_attention_override", None)
        if decode_attention is None:
            _resolve_decode_attention(self.decode_attention)
            decode_attention = self.decode_attention
        if self.d_model % self.num_heads != 0:
            raise ValueError(
                f"d_model={self.d_model} not divisible by "
                f"num_heads={self.num_heads}."
            )
        (seq_len,) = input_shape
        if self.max_seq_len == -1:
            max_seq_len = seq_len
        elif self.max_seq_len > 0:
            max_seq_len = self.max_seq_len
        else:
            # 0 or other negatives are config typos, not the sentinel —
            # silently auto-sizing them would hide the mistake.
            raise ValueError(
                f"max_seq_len={self.max_seq_len}: expected a positive "
                "capacity or -1 (size to the built sequence)."
            )
        if seq_len > max_seq_len:
            raise ValueError(
                f"seq_len {seq_len} exceeds max_seq_len {max_seq_len}."
            )
        return TransformerLMModule(
            vocab_size=num_classes,
            num_layers=self.num_layers,
            d_model=self.d_model,
            num_heads=self.num_heads,
            mlp_ratio=self.mlp_ratio,
            attention=attention,
            max_seq_len=max_seq_len,
            dtype=self.dtype(),
            decode_attention=decode_attention,
        )

    def initialize(
        self,
        module: nn.Module,
        input_shape: Sequence[int],
        seed: int = 0,
    ) -> Tuple[Any, Any]:
        """Token models init with an INT dummy (the base class's float
        zeros would be an invalid embedding index dtype)."""
        rng = jax.random.PRNGKey(seed)
        dummy = jnp.zeros((1, *input_shape), jnp.int32)
        variables = module.init(rng, dummy, training=False)
        params = variables.pop("params")
        return params, variables
