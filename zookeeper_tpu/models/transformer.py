"""Causal transformer language model — the long-context model family.

Beyond the reference's CNN contract (SURVEY.md §2.3 scopes the zoo to
image classifiers), but the brief makes long-context first-class and the
attention tiers (``ops.flash_attention`` / ``ring_attention`` /
``ring_flash_attention``) need a MODEL surface, not just bare ops: this
is the family that exercises them through the same ``Model`` /
``configure`` / ``TrainingExperiment`` machinery as the CNN zoo.

Design (TPU-first, standard pre-norm decoder):

- pre-RMSNorm blocks, GELU MLP, learned positional embedding, weight-
  tied LM head (embed.T) — the shapes XLA tiles well on the MXU
  (d_model/heads chosen so head_dim lands on 64/128 lanes);
- attention runs the Pallas flash kernel by default (``attention=
  "flash"``): O(block) VMEM at any sequence length, measured 2.5-5x
  faster fwd+bwd than the dense path and trains s=16k where dense OOMs
  (BASELINE.md round-7); ``"dense"`` keeps the reference oracle path;
- the module is pure (no mesh assumptions): data parallelism comes from
  the Partitioner sharding the batch; SEQUENCE parallelism composes at
  the ops layer (``ring_flash_attention`` inside a shard_map over a
  mesh with the sequence axis — see ``ops/attention.py``);
- the existing jittable train step works unchanged: ``softmax_cross_
  entropy`` and ``accuracy`` broadcast over the position dimension
  (logits ``[b, s, vocab]``, targets ``[b, s]``), so an LM batch is
  ``{"input": tokens, "target": next_tokens}`` and ``make_train_step``
  / ``TrainingExperiment`` need no LM-specific fork.
"""

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.models.base import Model
from zookeeper_tpu.ops import attention_reference, flash_attention
from zookeeper_tpu.parallel.sharding import constrain_batch_sharded


def _resolve_attention(attention):
    """``"flash"`` / ``"dense"`` / any ``callable(q, k, v, *, causal)``
    — the callable form is how sequence parallelism plugs in (e.g.
    ``partial(ring_flash_attention, mesh=mesh, seq_axis="sp",
    batch_axis="data")`` shards the attention over a mesh while the
    rest of the model runs an ordinary pjit program). Checked at the
    MODULE level too (it is public API): a typo'd tier must not
    silently fall back to dense — at s=16k that materializes the
    [s, s] scores and OOMs."""
    if callable(attention):
        return attention
    if attention == "flash":
        return flash_attention
    if attention == "dense":
        return attention_reference
    raise ValueError(
        f"attention={attention!r}: expected 'flash', 'dense', or an "
        "attention callable."
    )


class RMSNorm(nn.Module):
    """Root-mean-square layernorm (no mean subtraction, no bias): the
    cheaper norm that long-context transformer stacks standardized on;
    fp32 statistics regardless of compute dtype."""

    dtype: Any = jnp.float32
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        x32 = x.astype(jnp.float32)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        y = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        return (y * scale).astype(self.dtype)


class _Block(nn.Module):
    num_heads: int
    mlp_ratio: int
    attention: Any
    dtype: Any
    pin_activations: bool = True

    @nn.compact
    def __call__(self, x, training: bool):
        b, s, d = x.shape
        head_dim = d // self.num_heads

        h = RMSNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * d, use_bias=False, dtype=self.dtype, name="qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        to_heads = lambda t: t.reshape(b, s, self.num_heads, head_dim)
        attn = _resolve_attention(self.attention)
        o = attn(to_heads(q), to_heads(k), to_heads(v), causal=True)
        o = nn.Dense(
            d, use_bias=False, dtype=self.dtype, name="proj"
        )(o.reshape(b, s, d))
        x = x + o

        h = RMSNorm(dtype=self.dtype)(x)
        h = nn.Dense(
            self.mlp_ratio * d, use_bias=False, dtype=self.dtype, name="up"
        )(h)
        h = nn.gelu(h)
        h = nn.Dense(d, use_bias=False, dtype=self.dtype, name="down")(h)
        # Pin the residual stream to the canonical layout (batch on the
        # data axes) at every block boundary: without the pin, GSPMD
        # was observed picking an FSDP-axis-spread layout for the
        # attention intermediates it then could not reshard — the same
        # involuntary-full-remat pathology the CNN Quant layers pin
        # against (parallel/sharding.py). No-op outside a mesh scope;
        # see ``_auto_pin_activations`` for when the pin is skipped.
        out = x + h
        if self.pin_activations:
            out = constrain_batch_sharded(out)
        return out


def _auto_pin_activations(attention, pin_activations):
    """Whether the residual-stream pins apply. ``None`` (the default)
    auto-selects: pinned for the within-chip tiers (incl. the bare
    ``flash_attention``/``attention_reference`` callables — they are
    functionally identical to their string forms and need the same
    FSDP protection), skipped for any OTHER callable, which is assumed
    mesh-composed sequence parallelism: the SP op owns the
    sequence-sharded layout, and the ambient scope's canonical spec
    (which reads every non-data axis as a CHANNEL axis) would pin
    d_model over the sequence axis and fight it. Pass an explicit bool
    to override either way (e.g. ``True`` for a custom within-chip
    kernel under FSDP)."""
    if pin_activations is not None:
        return pin_activations
    return (
        not callable(attention)
        or attention in (flash_attention, attention_reference)
    )


class TransformerLMModule(nn.Module):
    vocab_size: int
    num_layers: int
    d_model: int
    num_heads: int
    mlp_ratio: int
    attention: Any  # "flash" | "dense" | callable(q, k, v, *, causal)
    max_seq_len: int
    dtype: Any
    #: None = auto (see ``_auto_pin_activations``); bool overrides.
    pin_activations: Any = None

    @nn.compact
    def __call__(self, tokens, training: bool = False):
        if tokens.ndim != 2:
            raise ValueError(
                f"TransformerLM expects [batch, seq] int tokens, got "
                f"shape {tokens.shape}."
            )
        s = tokens.shape[1]
        if s > self.max_seq_len:
            raise ValueError(
                f"Sequence length {s} exceeds max_seq_len "
                f"{self.max_seq_len} (the positional table size)."
            )
        embed = self.param(
            "embed",
            nn.initializers.normal(0.02),
            (self.vocab_size, self.d_model),
        )
        pos = self.param(
            "pos",
            nn.initializers.normal(0.02),
            (self.max_seq_len, self.d_model),
        )
        pin = _auto_pin_activations(self.attention, self.pin_activations)
        x = (embed[tokens] + pos[None, :s]).astype(self.dtype)
        if pin:
            x = constrain_batch_sharded(x)
        for i in range(self.num_layers):
            x = _Block(
                num_heads=self.num_heads,
                mlp_ratio=self.mlp_ratio,
                attention=self.attention,
                dtype=self.dtype,
                pin_activations=pin,
                name=f"block{i}",
            )(x, training)
        x = RMSNorm(dtype=self.dtype)(x)
        # Weight-tied LM head: logits in fp32 (the loss reduction dtype).
        return jnp.einsum(
            "bsd,vd->bsv", x.astype(jnp.float32), embed.astype(jnp.float32)
        )


def greedy_decode(
    module: nn.Module, variables: Any, prompt: Any, steps: int
) -> jax.Array:
    """Greedy argmax continuation: ``[batch, t0]`` int tokens ->
    ``[batch, t0 + steps]``. Each step recomputes the FULL context
    (one jitted forward per emitted token, no KV cache) — a smoke/debug
    utility for eyeballing what a trained LM memorized and the seed of
    a future incremental-decode serving path, not a serving path
    itself. Deterministic by construction (argmax, no sampling).

    The module's positional table bounds the total length: building
    with ``max_seq_len`` headroom (an explicit capacity larger than
    the training ``seq_len``) is what makes room to decode past the
    training window.
    """
    if steps < 0:
        raise ValueError(f"steps={steps} must be >= 0.")
    tokens = jnp.asarray(prompt)
    if tokens.ndim != 2:
        raise ValueError(
            f"prompt must be [batch, t0] int tokens, got {tokens.shape}."
        )
    cap = getattr(module, "max_seq_len", None)
    if cap is not None and tokens.shape[1] + steps > cap:
        raise ValueError(
            f"prompt length {tokens.shape[1]} + steps {steps} exceeds "
            f"the positional table capacity {cap}; build the model with "
            "a larger max_seq_len to decode further."
        )
    # One executable per total length (steps distinct compiles): fine
    # for a smoke utility; an incremental decoder would bucket lengths.
    forward = jax.jit(
        lambda v, t: module.apply(v, t, training=False)
    )
    for _ in range(int(steps)):
        logits = forward(variables, tokens)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(tokens.dtype)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return tokens


@component
class TransformerLM(Model):
    """Causal LM model component (see module docstring).

    ``build(input_shape=(seq_len,), num_classes=vocab_size)`` follows
    the Model contract — the "classes" of a language model are its
    vocabulary, scored at every position.
    """

    num_layers: int = Field(4)
    d_model: int = Field(256)
    num_heads: int = Field(4)
    mlp_ratio: int = Field(4)
    #: "flash" (Pallas kernels, long-context default) or "dense" (the
    #: oracle path).
    attention: str = Field("flash")
    #: Positional-table capacity. -1 (the default) sizes it to the
    #: sequence length ``build()`` receives — the common case, and it
    #: keeps one ``seq_len`` knob sufficient in CLI tasks. Set
    #: explicitly to train short now and run longer contexts later
    #: without a table reshape; build() raises if the configured
    #: sequence exceeds an explicit capacity.
    max_seq_len: int = Field(-1)

    def set_attention_override(self, fn) -> None:
        """The partitioner injection seam (``Partitioner.prepare_model``):
        a mesh-owning partitioner (``SequenceParallelPartitioner``)
        installs its attention callable here BEFORE ``build()``, which
        then takes precedence over the string ``attention`` Field — so
        sequence-parallel recipes drive from the CLI without hand-wiring
        callables into model configs. ``None`` clears the override."""
        if fn is not None and not callable(fn):
            raise ValueError(
                f"attention override must be callable(q, k, v, *, "
                f"causal) or None, got {fn!r}."
            )
        object.__setattr__(self, "_attention_override", fn)

    def build(self, input_shape: Sequence[int], num_classes: int) -> nn.Module:
        if len(input_shape) != 1:
            raise ValueError(
                f"TransformerLM input_shape must be (seq_len,), got "
                f"{tuple(input_shape)}."
            )
        # One source of truth for valid tiers (the Field is a string;
        # callables plug in at the MODULE level — see
        # ``_resolve_attention``). An injected override (the
        # partitioner seam above) wins over the Field.
        attention = getattr(self, "_attention_override", None)
        if attention is None:
            _resolve_attention(self.attention)
            attention = self.attention
        if self.d_model % self.num_heads != 0:
            raise ValueError(
                f"d_model={self.d_model} not divisible by "
                f"num_heads={self.num_heads}."
            )
        (seq_len,) = input_shape
        if self.max_seq_len == -1:
            max_seq_len = seq_len
        elif self.max_seq_len > 0:
            max_seq_len = self.max_seq_len
        else:
            # 0 or other negatives are config typos, not the sentinel —
            # silently auto-sizing them would hide the mistake.
            raise ValueError(
                f"max_seq_len={self.max_seq_len}: expected a positive "
                "capacity or -1 (size to the built sequence)."
            )
        if seq_len > max_seq_len:
            raise ValueError(
                f"seq_len {seq_len} exceeds max_seq_len {max_seq_len}."
            )
        return TransformerLMModule(
            vocab_size=num_classes,
            num_layers=self.num_layers,
            d_model=self.d_model,
            num_heads=self.num_heads,
            mlp_ratio=self.mlp_ratio,
            attention=attention,
            max_seq_len=max_seq_len,
            dtype=self.dtype(),
        )

    def initialize(
        self,
        module: nn.Module,
        input_shape: Sequence[int],
        seed: int = 0,
    ) -> Tuple[Any, Any]:
        """Token models init with an INT dummy (the base class's float
        zeros would be an invalid embedding index dtype)."""
        rng = jax.random.PRNGKey(seed)
        dummy = jnp.zeros((1, *input_shape), jnp.int32)
        variables = module.init(rng, dummy, training=False)
        params = variables.pop("params")
        return params, variables
