"""Full-precision ResNet family (BASELINE config #5: the non-binary path).

Standard pre-activation-free ResNet-v1.5 bottleneck architecture (He et
al. 2015, with the stride-on-3x3 variant) written directly in flax —
public-domain architecture, no code ported.
"""

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from zookeeper_tpu.core import Field, component
from zookeeper_tpu.models.base import Model
from zookeeper_tpu.ops.layers import BatchNorm


class _Bottleneck(nn.Module):
    features: int  # Bottleneck width; output is 4x.
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        bn = lambda: BatchNorm(  # noqa: E731
            use_running_average=not training, momentum=0.9, epsilon=1e-5,
            dtype=d,
        )
        out_features = self.features * 4
        shortcut = x
        if x.shape[-1] != out_features or self.strides > 1:
            shortcut = nn.Conv(
                out_features, (1, 1), strides=(self.strides, self.strides),
                use_bias=False, dtype=d,
            )(x)
            shortcut = bn()(shortcut)
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=d)(x)
        y = nn.relu(bn()(y))
        y = nn.Conv(
            self.features, (3, 3), strides=(self.strides, self.strides),
            padding="SAME", use_bias=False, dtype=d,
        )(y)
        y = nn.relu(bn()(y))
        y = nn.Conv(out_features, (1, 1), use_bias=False, dtype=d)(y)
        y = bn()(y)
        return nn.relu(y + shortcut)


class _ResNetModule(nn.Module):
    blocks_per_section: Tuple[int, ...]
    num_classes: int
    dtype: Any
    width: int = 64

    @nn.compact
    def __call__(self, x, training: bool = False):
        d = self.dtype
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d)(x.astype(d))
        x = BatchNorm(use_running_average=not training, momentum=0.9,
                         epsilon=1e-5, dtype=d)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for s, n in enumerate(self.blocks_per_section):
            for b in range(n):
                strides = 2 if (b == 0 and s > 0) else 1
                x = _Bottleneck(self.width * (2**s), strides, d)(x, training)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=d)(x)
        return x.astype(jnp.float32)


@component
class ResNet50(Model):
    """ResNet-50 (~76% top-1 target, BASELINE.md)."""

    blocks_per_section: Sequence[int] = Field((3, 4, 6, 3))
    width: int = Field(64)

    def build(self, input_shape, num_classes: int) -> nn.Module:
        return _ResNetModule(
            blocks_per_section=tuple(self.blocks_per_section),
            num_classes=num_classes,
            dtype=self.dtype(),
            width=self.width,
        )


@component
class ResNet101(ResNet50):
    blocks_per_section: Sequence[int] = Field((3, 4, 23, 3))


@component
class ResNet152(ResNet50):
    blocks_per_section: Sequence[int] = Field((3, 8, 36, 3))
