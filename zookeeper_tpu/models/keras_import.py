"""Keras/larq checkpoint migration: reference weights into this framework.

The reference ecosystem (zookeeper + larq + larq_zoo) stores trained
models as Keras checkpoints. A user switching to this framework brings
those weights along with :func:`import_keras_weights`, which maps a
built ``tf.keras`` model's variables onto a flax params/batch-stats
template by ALIGNED ORDER with strict shape checks.

Why order-based: Keras layer names ("conv2d_7") and flax scope names
("QuantConv_3") share nothing, but both frameworks enumerate layers in
construction order (flax params preserve call order), and both store
conv kernels HWIO and dense kernels [in, out] — so the i-th
weight-bearing Keras layer corresponds to the i-th weight slot of the
flax tree when the architectures match. Every assignment shape-checks,
and leftover slots on either side are loud errors, so a mismatched
architecture cannot import silently.

The one layout exception is ``Conv2DTranspose``: Keras stores its
kernel ``(kh, kw, out, in)`` with gradient-of-conv semantics, while
:class:`~zookeeper_tpu.ops.layers.QuantConvTranspose` uses JAX's native
``(kh, kw, in, out)`` un-flipped convention — :func:`keras_transpose_kernel`
converts (flip spatial axes, swap the trailing dims), and the import
applies it automatically for Keras layers of that class.

tensorflow is an optional dependency: these functions only TAKE a keras
model object, they never import tensorflow themselves.
"""

from collections.abc import Mapping
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["import_keras_weights", "keras_transpose_kernel"]

_KERNEL_KEYS = ("kernel", "kernel_fp")


def keras_transpose_kernel(kernel: np.ndarray) -> np.ndarray:
    """Convert a Keras ``Conv2DTranspose``/``Conv1DTranspose`` kernel
    ``(*spatial, out, in)`` (gradient-of-conv semantics) to this
    framework's ``(*spatial, in, out)`` un-flipped convention."""
    kernel = np.asarray(kernel)
    spatial = tuple(range(kernel.ndim - 2))
    flipped = np.flip(kernel, axis=spatial)
    return np.swapaxes(flipped, -1, -2)


def _flax_slots(
    params: Dict[str, Any], batch_stats: Optional[Dict[str, Any]]
) -> List[dict]:
    """Ordered weight slots from a flax params tree (call order — flax
    preserves scope-creation order): kernel slots (conv/dense, with
    optional bias) and BN slots (scale/bias + running stats).

    Any param leaf that fits NEITHER structure (custom learnables like
    ReActNet's RSign/RPReLU shifts, packed deployment kernels, ...) is a
    loud error: order-aligned import is only defined for conv/dense/BN
    architectures, and skipping unknown params would either desync the
    alignment or silently leave them at init values.
    """
    slots: List[dict] = []
    unmapped: List[str] = []

    def visit(node, stats_node, path):
        if not isinstance(node, Mapping):
            unmapped.append(path)
            return
        kernel_key = next((k for k in _KERNEL_KEYS if k in node), None)
        is_bn = "scale" in node and "bias" in node and kernel_key is None
        if kernel_key is not None:
            extra = set(node) - {kernel_key, "bias"}
            if extra:
                unmapped.extend(f"{path}/{k}" for k in sorted(extra))
            slots.append({
                "kind": "kernel",
                "path": path,
                "node": node,
                "kernel_key": kernel_key,
            })
            return
        if is_bn:
            extra = set(node) - {"scale", "bias"}
            if extra:
                unmapped.extend(f"{path}/{k}" for k in sorted(extra))
            slots.append({
                "kind": "bn",
                "path": path,
                "node": node,
                "stats": stats_node if isinstance(stats_node, Mapping) else None,
            })
            return
        for key, child in node.items():
            visit(
                child,
                (stats_node or {}).get(key) if stats_node else None,
                f"{path}/{key}" if path else key,
            )

    visit(params, batch_stats, "")
    if unmapped:
        raise ValueError(
            "Params tree has leaves the order-aligned Keras import cannot "
            f"map (not conv/dense kernels or BatchNorm scale/bias): "
            f"{unmapped[:8]}{'...' if len(unmapped) > 8 else ''}. Models "
            "with custom learnables (e.g. RSign/RPReLU shifts) or packed "
            "deployment params need a hand-written mapping."
        )
    return slots


def import_keras_weights(
    keras_model,
    params: Dict[str, Any],
    model_state: Optional[Dict[str, Any]] = None,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Map a built Keras model's weights onto flax ``(params,
    model_state)`` templates (e.g. from ``Model.initialize``); returns
    NEW trees, templates untouched. Raises with both sides named on any
    count or shape mismatch.
    """
    import jax.numpy as jnp

    def clone(tree):
        # Mapping, not dict: FrozenDict trees (older flax) traverse too;
        # the clone is a plain mutable dict either way.
        return {
            k: clone(v) if isinstance(v, Mapping) else v
            for k, v in tree.items()
        }

    new_params = clone(params)
    new_state = clone(model_state or {})
    slots = _flax_slots(new_params, new_state.get("batch_stats"))

    def assign(node, key, value, what):
        template = node[key]
        value = np.asarray(value)
        if tuple(template.shape) != tuple(value.shape):
            raise ValueError(
                f"{what}: keras weight shape {tuple(value.shape)} does "
                f"not match template {tuple(template.shape)}."
            )
        node[key] = jnp.asarray(value, template.dtype)

    slot_iter = iter(slots)
    for layer in keras_model.layers:
        weights = layer.get_weights()
        if not weights:
            continue
        cls = type(layer).__name__
        try:
            slot = next(slot_iter)
        except StopIteration:
            raise ValueError(
                f"Keras layer {layer.name!r} ({cls}) has no remaining "
                "flax weight slot — architectures differ."
            ) from None
        what = f"keras {layer.name!r} ({cls}) -> flax {slot['path']!r}"
        if cls == "BatchNormalization":
            if slot["kind"] != "bn" or len(weights) != 4:
                raise ValueError(
                    f"{what}: expected a BatchNorm slot and 4 weights "
                    f"(gamma, beta, moving_mean, moving_var; scale and "
                    f"center enabled), got slot kind {slot['kind']!r} "
                    f"and {len(weights)} weights."
                )
            gamma, beta, mean, var = weights
            assign(slot["node"], "scale", gamma, what)
            assign(slot["node"], "bias", beta, what)
            if slot["stats"] is None:
                raise ValueError(
                    f"{what}: template has no batch_stats for this "
                    "BatchNorm (pass model_state)."
                )
            assign(slot["stats"], "mean", mean, what)
            assign(slot["stats"], "var", var, what)
            continue
        if slot["kind"] != "kernel" or len(weights) not in (1, 2):
            raise ValueError(
                f"{what}: expected a kernel slot and 1-2 weights "
                f"(kernel[, bias]), got slot kind {slot['kind']!r} and "
                f"{len(weights)} weights."
            )
        kernel = weights[0]
        if "Transpose" in cls:
            kernel = keras_transpose_kernel(kernel)
        assign(slot["node"], slot["kernel_key"], kernel, what)
        if len(weights) == 2:
            if "bias" not in slot["node"]:
                raise ValueError(
                    f"{what}: keras layer has a bias but the flax layer "
                    "does not (use_bias mismatch)."
                )
            assign(slot["node"], "bias", weights[1], what)
        elif "bias" in slot["node"]:
            raise ValueError(
                f"{what}: flax layer has a bias but the keras layer "
                "does not (use_bias mismatch)."
            )
    leftover = [s["path"] for s in slot_iter]
    if leftover:
        raise ValueError(
            f"Keras model exhausted but flax slots remain: {leftover} — "
            "architectures differ."
        )
    return new_params, new_state
