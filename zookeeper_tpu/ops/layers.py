"""Quantized flax layers with latent full-precision weights.

TPU-native `QuantDense` / `QuantConv` (the larq `QuantDense`/`QuantConv2D`
capability, SURVEY.md §2.4): the *latent* kernel lives in fp32 and is
quantized on the forward pass; gradients flow to the latent weights through
the quantizer's STE. ``kernel_clip`` emulates larq's ``weight_clip``
constraint by clamping latent weights into [-1, 1] inside the forward
(projection happens on read, so the optimizer state stays untouched and
the op fuses into the conv under XLA).

The binary inference fast path (bit-packed XNOR-popcount via Pallas) swaps
in behind the same module interface; training keeps the float path where
XLA's MXU convs on +-1.0 values are already optimal.
"""

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp

from zookeeper_tpu.ops.quantizers import get_quantizer

Quantizer = Union[str, Callable, None]


def _apply_clip(kernel: jax.Array, clip: bool) -> jax.Array:
    if not clip:
        return kernel
    # Straight-through projection: forward sees clipped weights, gradients
    # pass through unclipped (larq weight_clip semantics: the constraint
    # projects after each update; reading-time clamp + STE is equivalent at
    # the fixed point and jit-friendly).
    clipped = jnp.clip(kernel, -1.0, 1.0)
    return kernel + jax.lax.stop_gradient(clipped - kernel)


class QuantDense(nn.Module):
    """Dense layer with optional input/kernel quantization."""

    features: int
    input_quantizer: Quantizer = None
    kernel_quantizer: Quantizer = None
    kernel_clip: bool = True
    use_bias: bool = True
    dtype: Any = jnp.float32
    kernel_init: Callable = nn.initializers.glorot_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_q = get_quantizer(self.input_quantizer)
        k_q = get_quantizer(self.kernel_quantizer)
        kernel = self.param(
            "kernel", self.kernel_init, (x.shape[-1], self.features), jnp.float32
        )
        if in_q is not None:
            x = in_q(x)
        kernel = _apply_clip(kernel, self.kernel_clip)
        if k_q is not None:
            kernel = k_q(kernel)
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,), jnp.float32)
            y = y + bias.astype(self.dtype)
        return y


class QuantConv(nn.Module):
    """2-D convolution with optional input/kernel quantization (NHWC).

    ``binary_compute`` selects the executable path when BOTH operands are
    binarized: "mxu" (default — XLA conv on +-1 values in ``dtype``) or
    "int8" (int8 operands, int32 MXU accumulation — 2x bf16 MXU peak,
    bit-exact, STE gradients preserved via custom_vjp).
    """

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: Union[str, Sequence[Tuple[int, int]]] = "SAME"
    input_quantizer: Quantizer = None
    kernel_quantizer: Quantizer = None
    kernel_clip: bool = True
    use_bias: bool = False
    dtype: Any = jnp.float32
    binary_compute: str = "mxu"
    kernel_init: Callable = nn.initializers.glorot_normal()
    bias_init: Callable = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_q = get_quantizer(self.input_quantizer)
        k_q = get_quantizer(self.kernel_quantizer)
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel",
            self.kernel_init,
            (kh, kw, x.shape[-1], self.features),
            jnp.float32,
        )
        if in_q is not None:
            x = in_q(x)
        kernel = _apply_clip(kernel, self.kernel_clip)
        if k_q is not None:
            kernel = k_q(kernel)
        if (
            self.binary_compute == "int8"
            and in_q is not None
            and k_q is not None
            and isinstance(self.padding, str)
        ):
            from zookeeper_tpu.ops.binary_compute import int8_conv

            y = int8_conv(x, kernel, tuple(self.strides), self.padding)
            y = y.astype(self.dtype)
        else:
            y = jax.lax.conv_general_dilated(
                x.astype(self.dtype),
                kernel.astype(self.dtype),
                window_strides=self.strides,
                padding=self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        if self.use_bias:
            bias = self.param("bias", self.bias_init, (self.features,), jnp.float32)
            y = y + bias.astype(self.dtype)
        return y
